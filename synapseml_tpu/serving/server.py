"""HTTP ⇄ Dataset serving.

Re-designs Spark Serving (reference: core/src/main/scala/org/apache/spark/
sql/execution/streaming/HTTPSourceV2.scala:56-90 — an HttpServer hosted in
a partition task turning requests into rows {id, request}; ServingUDFs.
scala:40-53 — ``sendReplyUDF`` routing response bytes back to the open
exchange by request id; DistributedHTTPSource.scala:88,203 — ONE server per
JVM hosting MULTIPLE named APIs).  Here the source/sink pair is explicit:

- :class:`ServingServer` hosts any number of registered APIs on one
  listener; each API owns a bounded micro-batch queue (backpressure: a
  full queue answers 503 immediately instead of parking the exchange) and
  a pending-exchange map keyed by request id.
- :class:`PipelineServer` is the continuous-serving loop for one API —
  batch → ``model.transform`` → reply — so the jitted model sees
  fixed-size batches instead of per-request calls.
- :class:`MultiPipelineServer` runs several named pipelines on one
  server, one serving loop per API (the multi-API routing of
  HTTPSourceV2's ServiceInfo registry).
- ``GET /metrics`` is a RESERVED path on every listener: it exposes the
  process-wide :mod:`synapseml_tpu.telemetry` registry as Prometheus
  text (JSON with ``?format=json``), and serving loops feed it
  per-API record counters, batch-size histograms, and a records/sec
  throughput gauge.
- ``GET /healthz`` and ``GET /readyz`` are likewise RESERVED
  (:mod:`synapseml_tpu.resilience.health`): liveness is the listener
  answering at all; readiness flips to 503 + ``Retry-After`` while
  draining.  Load-shedding 503s (saturated queue, stale batch) carry a
  ``Retry-After`` computed from queue depth over the observed drain
  rate, and :meth:`ServingServer.drain` stops accepting, flushes every
  accepted in-flight exchange, then closes — zero dropped work.
"""

from __future__ import annotations

import asyncio
import json
import math
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.client import responses as _http_reasons
from queue import Empty, Full, Queue
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataset import Dataset
from ..core.pipeline import Transformer
from ..resilience.health import HealthState, retry_after_from_depth
from ..telemetry import (PROMETHEUS_CONTENT_TYPE, SERVING_TOKEN_LATENCY_BUCKETS,
                         SERVING_TTFT_BUCKETS, check_sloz, get_registry,
                         get_request_tracer, get_slo_store, render_json,
                         render_prometheus)
from ..telemetry.flight import record as _flight_record

#: request header (lower-cased, as the listener normalizes) carrying a
#: propagated request trace id across serving hops; replies echo it
#: back in canonical case so a client/balancer can stitch the hop chain
TRACE_HEADER = "x-sml-trace-id"
#: the reply-side spelling of the SAME contract — derived, so a header
#: rename can never desync the echo from what clients read
TRACE_HEADER_CANONICAL = "-".join(
    p.upper() if p == "sml" else p.capitalize()
    for p in TRACE_HEADER.split("-"))

#: request header (lower-cased) naming the tenant a request bills to —
#: the multi-tenant QoS plane keys admission weights, shed budgets, and
#: SLO attribution by it; absent ⇒ the default tenant, so single-tenant
#: traffic is untouched
TENANT_HEADER = "x-sml-tenant"
TENANT_HEADER_CANONICAL = "-".join(
    p.upper() if p == "sml" else p.capitalize()
    for p in TENANT_HEADER.split("-"))

#: every reserved ``GET`` path a ServingServer listener answers before
#: API routing.  The tier-1 endpoint-docs lint asserts (a) this tuple
#: and ``ServingServer._reserved_handler`` agree with the dispatch
#: source and (b) each path is documented in docs/api/serving.md — a
#: future endpoint cannot land undocumented.
RESERVED_GET_PATHS = ("/metrics", "/healthz", "/readyz", "/tracez", "/sloz",
                      "/tunez")


@dataclass
class ServingRequest:
    """One pending request row (reference: HTTPSourceV2 row schema
    {id, request})."""
    id: str
    method: str
    path: str
    headers: Dict[str, str]
    body: bytes
    #: monotonic enqueue time — lets serving loops bound queue wait
    enqueued_at: float = 0.0
    #: propagated request trace id (the ``X-SML-Trace-Id`` header when
    #: the client/balancer minted one upstream; None ⇒ the serving loop
    #: mints its own subject to sampling)
    trace_id: Optional[str] = None
    #: billing/QoS tenant (the ``X-SML-Tenant`` header, overridable by
    #: a ``tenant`` payload field); every pre-existing caller lands on
    #: the default tenant with unchanged behavior
    tenant: str = "default"
    #: priority class override carried by the request (``priority``
    #: payload field); None ⇒ the tenant policy's class applies
    priority: Optional[int] = None

    def json(self) -> Any:
        return json.loads(self.body.decode("utf-8"))


@dataclass
class ServingReply:
    status: int = 200
    body: bytes = b""
    headers: Dict[str, str] = field(default_factory=dict)


class _Exchange:
    __slots__ = ("request", "event", "reply", "waiter")

    def __init__(self, request: ServingRequest):
        self.request = request
        self.event = threading.Event()
        self.reply: Optional[ServingReply] = None
        #: (loop, future) for the asyncio listener awaiting this reply
        self.waiter = None


class ApiHandle:
    """One named API's source/sink pair: bounded request queue + pending
    exchanges.  ``get_batch``/``reply`` mirror HTTPSourceV2 getBatch and
    ServingUDFs.sendReplyUDF for this API only."""

    def __init__(self, path: str, max_queue: int = 1024,
                 reply_timeout_s: float = 30.0):
        self.path = path
        self.max_queue = max_queue
        self.reply_timeout_s = reply_timeout_s
        self._queue: "Queue[_Exchange]" = Queue(maxsize=max_queue)
        self._pending: Dict[str, _Exchange] = {}
        self._lock = threading.Lock()

    # -- server side -------------------------------------------------------
    def submit(self, req: ServingRequest) -> Optional[_Exchange]:
        """Enqueue; None ⇒ queue saturated (caller answers 503).

        Registered in ``_pending`` BEFORE the queue put: a fast pipeline
        can drain + reply the instant the exchange is visible, and a reply
        must find the registration or it would be silently dropped."""
        req.enqueued_at = time.monotonic()
        ex = _Exchange(req)
        with self._lock:
            self._pending[req.id] = ex
        try:
            self._queue.put_nowait(ex)
        except Full:
            with self._lock:
                self._pending.pop(req.id, None)
            return None
        return ex

    def forget(self, request_id: str) -> None:
        with self._lock:
            self._pending.pop(request_id, None)

    # -- source side (micro-batch pull; HTTPSourceV2 getBatch analogue) ----
    def get_batch(self, max_rows: int = 64,
                  timeout_s: float = 0.05) -> List[ServingRequest]:
        """Block up to ``timeout_s`` for the first request, then drain only
        what is already queued — continuous-mode semantics: a lone request
        is served immediately instead of waiting out the batch window,
        while a burst still rides one batched transform.

        ``timeout_s <= 0`` is the non-blocking fast path (``poll``): a
        decode loop with sequences in flight must never stall a running
        batch waiting on new arrivals."""
        if timeout_s <= 0:
            return self.poll(max_rows)
        out: List[_Exchange] = []
        try:
            out.append(self._queue.get(timeout=timeout_s))
        except Empty:
            return []
        while len(out) < max_rows:
            try:
                out.append(self._queue.get_nowait())
            except Empty:
                break
        return [e.request for e in out]

    def poll(self, max_rows: int = 64) -> List[ServingRequest]:
        """Non-blocking :meth:`get_batch`: return whatever is already
        queued (possibly nothing) without waiting — the admission path
        of a continuous-batching loop, which checks for new arrivals
        EVERY decode step and must not park the in-flight batch."""
        out: List[_Exchange] = []
        while len(out) < max_rows:
            try:
                out.append(self._queue.get_nowait())
            except Empty:
                break
        return [e.request for e in out]

    # -- sink side (ServingUDFs.sendReplyUDF analogue) ---------------------
    def reply(self, request_id: str, reply: ServingReply) -> bool:
        with self._lock:
            ex = self._pending.get(request_id)
        if ex is None:
            return False
        ex.reply = reply
        ex.event.set()
        w = ex.waiter
        if w is not None:
            loop, fut = w
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(None))
        return True


class ServingServer:
    """One HTTP listener per host hosting any number of named APIs (the
    DistributedHTTPSource model — one server per JVM, many sources;
    multi-host serving runs one per TPU-VM worker behind an external
    balancer).  The single-API constructor arguments keep the original
    one-endpoint usage working unchanged."""

    #: process-wide instance counter — names each server's health series
    _instances = 0
    _instances_lock = threading.Lock()

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout_s: float = 30.0,
                 max_queue: int = 1024,
                 max_body_bytes: int = 16 * 1024 * 1024):
        #: requests larger than this answer 413 and close — an unbounded
        #: readexactly would let one request allocate arbitrary memory
        self.max_body_bytes = max_body_bytes
        self.api_path = api_path.rstrip("/") or "/"
        self._apis: Dict[str, ApiHandle] = {}
        self._apis_lock = threading.Lock()
        with ServingServer._instances_lock:
            ServingServer._instances += 1
            self.health = HealthState(f"serving-{ServingServer._instances}")
        #: accepted exchanges not yet fully written back (loop-thread only)
        self._inflight = 0
        self._default = self.register_api(self.api_path, max_queue,
                                          reply_timeout_s)
        self._addr: Tuple[str, int] = (host, port)
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._start_error: Optional[BaseException] = None
        self._closed = False
        self._aserver = None
        self._thread = threading.Thread(target=self._run_loop,
                                        args=(host, port), daemon=True)
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("serving listener failed to start")
        if self._start_error is not None:    # e.g. EADDRINUSE, synchronous
            raise self._start_error

    # -- asyncio listener --------------------------------------------------
    # One event loop handles every connection: no per-request threads, so a
    # 64-way burst costs 64 coroutines instead of 64 OS threads fighting
    # the GIL — measured on the 1-core CI host this cut the load-test p99
    # from ~450-900 ms to the tens of milliseconds.  Pipeline work still
    # runs on the _ApiLoop worker threads; the loop only parses, enqueues,
    # and awaits each exchange's reply future.

    def _run_loop(self, host: str, port: int) -> None:
        asyncio.set_event_loop(self._loop)

        async def _start():
            self._aserver = await asyncio.start_server(
                self._handle_conn, host, port, backlog=256)
            self._addr = self._aserver.sockets[0].getsockname()[:2]
            self._started.set()

        try:
            self._loop.run_until_complete(_start())
        except BaseException as e:      # surface bind errors to the caller
            self._start_error = e
            self._started.set()
            self._loop.close()
            return
        try:
            self._loop.run_forever()
        finally:
            try:
                self._loop.run_until_complete(
                    self._loop.shutdown_asyncgens())
            finally:
                self._loop.close()

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                parts = line.decode("latin1").split()
                if len(parts) < 2:
                    break
                method, path = parts[0], parts[1]
                # header keys lower-cased: HTTP headers are
                # case-insensitive (the old BaseHTTPRequestHandler was too)
                headers: Dict[str, str] = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                if headers.get("upgrade", "").lower() == "sml-frames":
                    # continuous mode: the connection leaves HTTP for a
                    # length-prefixed frame stream (the reference's
                    # continuousServer analogue — one parse-free exchange
                    # per record instead of one HTTP request)
                    await self._handle_frames(reader, writer, path)
                    break
                te = headers.get("transfer-encoding", "").lower()
                if "chunked" in te:
                    body = await self._read_chunked(reader, writer)
                    if body is None:       # oversize: 413 already written
                        break
                else:
                    try:
                        length = int(headers.get("content-length", 0) or 0)
                    except ValueError:
                        writer.write(b"HTTP/1.1 400 Bad Request\r\n"
                                     b"Content-Length: 0\r\n"
                                     b"Connection: close\r\n\r\n")
                        await writer.drain()
                        break
                    if length > self.max_body_bytes:
                        await self._write_413(writer)
                        break
                    body = await reader.readexactly(length) if length else b""
                # in-flight from dispatch until the reply is fully written:
                # drain() waits on this so an accepted exchange can never
                # lose the race between computing its reply and the
                # listener closing
                self._inflight += 1
                try:
                    status, rbody, rheaders = await self._dispatch(
                        method, path, headers, body)
                    keep = headers.get("connection", "").lower() != "close"
                    reason = _http_reasons.get(status, "Unknown")
                    head = [f"HTTP/1.1 {status} {reason}"]
                    ctype_set = False
                    for k, v in rheaders.items():
                        head.append(f"{k}: {v}")
                        ctype_set = ctype_set or k.lower() == "content-type"
                    if not ctype_set:
                        head.append("Content-Type: application/json")
                    if isinstance(rbody, (bytes, bytearray)):
                        head.append(f"Content-Length: {len(rbody)}")
                        head.append("Connection: " + ("keep-alive" if keep
                                                      else "close"))
                        writer.write(("\r\n".join(head) + "\r\n\r\n")
                                     .encode("latin1") + bytes(rbody))
                        await writer.drain()
                    else:
                        # streaming reply: an ITERABLE body goes out with
                        # chunked transfer-encoding (the reference's
                        # continuous-mode reply stream)
                        head.append("Transfer-Encoding: chunked")
                        head.append("Connection: " + ("keep-alive" if keep
                                                      else "close"))
                        writer.write(("\r\n".join(head) + "\r\n\r\n")
                                     .encode("latin1"))
                        # pull chunks on a worker thread: a generator that
                        # blocks between yields (live token streams) must
                        # not stall the event loop for every other
                        # connection.  A write failure (client gone
                        # mid-stream) tells an abandonable body before
                        # propagating, so a live token stream's producer
                        # can stop decoding for the dead connection
                        it = iter(rbody)
                        _end = object()
                        try:
                            while True:
                                chunk = await self._loop.run_in_executor(
                                    None, next, it, _end)
                                if chunk is _end:
                                    break
                                chunk = bytes(chunk)
                                if not chunk:
                                    continue
                                writer.write(
                                    f"{len(chunk):x}\r\n".encode("latin1")
                                    + chunk + b"\r\n")
                                await writer.drain()
                            writer.write(b"0\r\n\r\n")
                            await writer.drain()
                        except BaseException:
                            abandon = getattr(rbody, "abandon", None)
                            if abandon is not None:
                                abandon()
                            raise
                finally:
                    self._inflight -= 1
                if not keep:
                    break
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, asyncio.LimitOverrunError, ValueError):
            pass      # truncated/oversized/undecodable request: drop conn
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _await_reply(self, api: ApiHandle, ex: _Exchange):
        """Attach this loop's waiter to ``ex`` and await its reply — the
        ONE place the waiter-attach race and reply timeout live for both
        the HTTP and frame paths.  The timeout is anchored at SUBMIT time
        (``enqueued_at``), so pipelined frames awaited serially do not
        compound each other's timeouts.  Always forgets the exchange;
        raises ``asyncio.TimeoutError`` on expiry; returns the
        ServingReply (None when the pipeline replied nothing)."""
        fut = self._loop.create_future()
        ex.waiter = (self._loop, fut)
        if ex.event.is_set() and not fut.done():       # reply raced attach
            fut.set_result(None)
        remaining = max(
            ex.request.enqueued_at + api.reply_timeout_s - time.monotonic(),
            0.0)
        try:
            await asyncio.wait_for(fut, remaining)
        finally:
            api.forget(ex.request.id)
        return ex.reply

    async def _handle_frames(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter,
                             path: str) -> None:
        """Continuous (framed) mode: ``Upgrade: sml-frames``.

        The reference's ``continuousServer`` keeps the exchange open and
        streams record-at-a-time replies (spark_serving/about.md's
        sub-millisecond continuous mode); the analogue here upgrades the
        connection to a binary frame stream so the per-record cost drops
        to one length-prefixed read — no request line, headers, routing,
        or reply-head formatting per record.

        Wire format: requests are ``u32le length + payload``; replies are
        ``u32le (2+len) + u16le status + body``, always in request order
        (a per-connection BOUNDED fifo of pending exchanges — a full
        fifo backpressures the frame reader, so one fast client cannot
        grow server memory without bound).  Client EOF ends the stream;
        queued replies flush before close, and whatever neither side
        consumed is forgotten so ``_pending`` never leaks."""
        import struct

        api = self._route(path)
        if api is None:
            writer.write(b"HTTP/1.1 404 Not Found\r\nContent-Length: 0\r\n"
                         b"Connection: close\r\n\r\n")
            await writer.drain()
            return
        writer.write(b"HTTP/1.1 101 Switching Protocols\r\n"
                     b"Upgrade: sml-frames\r\nConnection: Upgrade\r\n\r\n")
        await writer.drain()
        conn = uuid.uuid4().hex
        fifo: "asyncio.Queue" = asyncio.Queue(maxsize=max(api.max_queue, 1))

        async def write_replies():
            while True:
                item = await fifo.get()
                if item is None:
                    return
                try:
                    if item[0] == "now":
                        status, body = item[1]
                    else:
                        try:
                            rep = await self._await_reply(api, item[1])
                            status = rep.status if rep else 500
                            body = (rep.body if rep
                                    else b'{"error": "empty reply"}')
                            if not isinstance(body, (bytes, bytearray)):
                                # frames are single messages; stream bodies
                                # (iterables) concatenate
                                body = b"".join(bytes(c) for c in body)
                        except asyncio.TimeoutError:
                            status = 504
                            body = b'{"error": "serving pipeline timeout"}'
                    writer.write(struct.pack("<IH", 2 + len(body), status)
                                 + bytes(body))
                    await writer.drain()
                finally:
                    self._inflight -= 1        # enqueued by the read loop

        wtask = asyncio.ensure_future(write_replies())

        async def fifo_put(item) -> bool:
            """Bounded put that cannot deadlock on a dead writer: a plain
            ``await fifo.put`` on a full fifo blocks forever once the
            writer task has died (nothing consumes), leaking the handler
            and every queued exchange — poll instead, and report failure
            when the writer is gone."""
            while True:
                try:
                    fifo.put_nowait(item)
                    return True
                except asyncio.QueueFull:
                    if wtask.done():
                        return False
                    # race the blocking put against the writer's death so
                    # a freed slot wakes us immediately (no poll latency
                    # on the live-writer backpressure path)
                    put = asyncio.ensure_future(fifo.put(item))
                    try:
                        await asyncio.wait({put, wtask},
                                           return_when=asyncio.FIRST_COMPLETED)
                        if put.done() and put.exception() is None:
                            return True
                    finally:
                        # also on handler cancellation: never orphan the
                        # put task (it could enqueue after the drain ran)
                        if not put.done():
                            put.cancel()
                            try:
                                await put
                            except (asyncio.CancelledError, Exception):
                                pass

        seq = 0
        try:
            while True:
                hdr = await reader.readexactly(4)
                (ln,) = struct.unpack("<I", hdr)
                if ln > self.max_body_bytes:
                    if not wtask.done():
                        self._inflight += 1
                        if not await fifo_put(("now", (413, b""))):
                            self._inflight -= 1
                    break
                payload = await reader.readexactly(ln) if ln else b""
                if not self.health.ready:      # draining: shed new frames
                    self._inflight += 1
                    if not await fifo_put(
                            ("now", (503, b'{"error": "server '
                                          b'draining"}'))):
                        self._inflight -= 1
                        break
                    continue
                req = ServingRequest(id=f"{conn}:{seq}", method="FRAME",
                                     path=path, headers={}, body=payload)
                seq += 1
                ex = api.submit(req)
                if wtask.done():          # writer died: stop accepting
                    if ex is not None:
                        api.forget(req.id)
                    break
                if ex is None:                          # backpressure
                    self._inflight += 1
                    if not await fifo_put(
                            ("now", (503, b'{"error": "serving queue '
                                          b'saturated"}'))):
                        self._inflight -= 1
                        break
                    continue
                self._inflight += 1
                if not await fifo_put(("ex", ex)):      # writer died
                    self._inflight -= 1
                    api.forget(req.id)
                    break
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError):
            pass                                        # client went away
        finally:
            if not wtask.done():
                await fifo_put(None)                    # flush in order
            try:
                await wtask
            except (ConnectionResetError, BrokenPipeError):
                pass
            finally:
                # forget exchanges neither flushed nor timed out (writer
                # died mid-burst) so ApiHandle._pending cannot leak —
                # runs even when wtask re-raises something unexpected
                while not fifo.empty():
                    item = fifo.get_nowait()
                    if item is not None:
                        self._inflight -= 1     # writer never consumed it
                        if item[0] == "ex":
                            api.forget(item[1].request.id)

    async def _write_413(self, writer: asyncio.StreamWriter) -> None:
        writer.write(b"HTTP/1.1 413 Payload Too Large\r\n"
                     b"Content-Length: 0\r\nConnection: close\r\n\r\n")
        await writer.drain()

    async def _read_chunked(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> Optional[bytes]:
        """Decode a chunked request body (size cap enforced; None ⇒ the
        connection must close).  Trailer section is consumed and ignored."""
        parts: List[bytes] = []
        total = 0
        while True:
            line = await reader.readline()
            if not line:
                # EOF mid-body: a truncated upload must NOT dispatch as a
                # complete request (the Content-Length path's
                # IncompleteReadError equivalent)
                raise asyncio.IncompleteReadError(b"", None)
            size = int(line.split(b";")[0].strip() or b"0", 16)
            if size == 0:
                break
            total += size
            if total > self.max_body_bytes:
                await self._write_413(writer)
                return None
            parts.append(await reader.readexactly(size))
            await reader.readexactly(2)                # chunk CRLF
        while True:                                    # trailers
            t = await reader.readline()
            if t in (b"\r\n", b"\n", b""):
                break
        return b"".join(parts)

    # -- health / load-shedding helpers ------------------------------------
    def _queue_depth(self) -> int:
        """Accepted-but-unanswered work across every API.  ``_pending``
        alone is exact: submit registers there BEFORE the queue put and
        entries leave only on reply/forget, so queued exchanges are a
        subset (adding ``_queue.qsize()`` would double-count them and
        inflate Retry-After hints up to 2x)."""
        with self._apis_lock:
            handles = list(self._apis.values())
        return sum(len(h._pending) for h in handles)

    def _drain_rps(self) -> float:
        """Best observed per-API throughput — the denominator of the
        Retry-After hint (0 when nothing has been served yet)."""
        g = get_registry().get("serving_records_per_sec")
        best = 0.0
        if g is not None:
            for _, val in g.series().items():
                try:
                    best = max(best, float(val))  # type: ignore[arg-type]
                except (TypeError, ValueError):
                    pass
        return best

    def _shed_headers(self) -> Dict[str, str]:
        ra = retry_after_from_depth(self._queue_depth(), self._drain_rps())
        return {"Retry-After": str(ra)}

    # -- reserved GET endpoints --------------------------------------------
    def _reserved_handler(self, bare: str):
        """Handler for one RESERVED_GET_PATHS entry (None when ``bare``
        is not reserved) — served before API routing, even while
        draining.  One map, one tuple: the tier-1 endpoint-docs lint
        cross-checks both against docs/api/serving.md."""
        return {"/metrics": self._serve_metrics,
                "/healthz": self._serve_healthz,
                "/readyz": self._serve_readyz,
                "/tracez": self._serve_tracez,
                "/sloz": self._serve_sloz,
                "/tunez": self._serve_tunez}.get(bare)

    def _serve_healthz(self, query: str, headers: Dict[str, str]):
        return self.health.healthz()

    def _serve_readyz(self, query: str, headers: Dict[str, str]):
        return self.health.readyz(self._queue_depth(), self._drain_rps())

    def _serve_metrics(self, query: str, headers: Dict[str, str]):
        # the process metrics registry as Prometheus text, or JSON with
        # ?format=json / an application/json Accept header
        want_json = ("format=json" in query
                     or "application/json" in headers.get("accept", ""))
        if want_json:
            body, ctype = render_json().encode("utf-8"), "application/json"
        else:
            body, ctype = (render_prometheus().encode("utf-8"),
                           PROMETHEUS_CONTENT_TYPE)
        return 200, body, {"Content-Type": ctype}

    def _serve_tracez(self, query: str, headers: Dict[str, str]):
        """Recent request timelines from the process
        :class:`~synapseml_tpu.telemetry.tracing.RequestTraceStore`;
        ``?id=<trace_id>`` exports ONE request as Chrome-trace JSON
        (chrome://tracing / Perfetto), ``?limit=N`` bounds the listing."""
        from urllib.parse import parse_qs
        params = parse_qs(query)
        store = get_request_tracer()
        trace_id = (params.get("id") or [None])[0]
        if trace_id is not None:
            trace = store.chrome_trace(trace_id)
            if trace is None:
                return (404, json.dumps(
                    {"error": f"no trace {trace_id!r} retained"}).encode(),
                    {"Content-Type": "application/json"})
            payload = trace
        else:
            try:
                limit = int((params.get("limit") or ["50"])[0])
            except ValueError:
                limit = 50
            payload = store.snapshot(limit)
        return 200, json.dumps(payload).encode("utf-8"), {
            "Content-Type": "application/json"}

    def _serve_sloz(self, query: str, headers: Dict[str, str]):
        """The windowed SLO snapshot (the autoscaler input contract):
        schema-validated BEFORE serving — a malformed window answers
        500, never a silently wrong consumer input.  ``?tenant=<id>``
        filters to that tenant's attribution planes (named
        ``<base>@tenant=<id>``) so one tenant's burn rate is readable
        without digging it out of aggregate percentiles;
        ``?phase=prefill|decode`` is the same filter over the
        disaggregated per-phase planes (``<base>@phase=<p>``) — the
        per-phase autoscalers each consume one filtered view."""
        from urllib.parse import parse_qs
        from ..telemetry.slo import plane_phase, plane_tenant
        params = parse_qs(query)
        tenant = (params.get("tenant") or [None])[0]
        phase = (params.get("phase") or [None])[0]
        snap = get_slo_store().snapshot()
        if tenant is not None:
            snap["planes"] = {name: plane
                              for name, plane in snap["planes"].items()
                              if plane_tenant(name) == tenant}
        if phase is not None:
            snap["planes"] = {name: plane
                              for name, plane in snap["planes"].items()
                              if plane_phase(name) == phase}
        try:
            check_sloz(snap, tenant=tenant, phase=phase)
        except ValueError as e:
            return (500, json.dumps(
                {"error": f"sloz snapshot failed validation: {e}"}).encode(),
                {"Content-Type": "application/json"})
        return 200, json.dumps(snap).encode("utf-8"), {
            "Content-Type": "application/json"}

    def _serve_tunez(self, query: str, headers: Dict[str, str]):
        """The autotune tuning-table snapshot: per-space winner with its
        measured ms and provenance (``source``/``measured_unix``/
        ``device_kind``), staleness against the plane's max age, and the
        consult log — which construction sites loaded (or refused) the
        table in THIS process.  Schema-validated BEFORE serving (the
        ``/sloz`` discipline); ``?space=<name>`` filters entries and
        consults to one search space."""
        from urllib.parse import parse_qs
        from ..telemetry.tunetable import check_tunez, get_tuneplane
        params = parse_qs(query)
        space = (params.get("space") or [None])[0]
        snap = get_tuneplane().snapshot()
        if space is not None:
            snap["entries"] = [e for e in snap["entries"]
                               if e.get("space") == space]
            snap["consults"] = [c for c in snap["consults"]
                                if c.get("space") == space]
        try:
            check_tunez(snap)
        except ValueError as e:
            return (500, json.dumps(
                {"error": f"tunez snapshot failed validation: {e}"}).encode(),
                {"Content-Type": "application/json"})
        return 200, json.dumps(snap).encode("utf-8"), {
            "Content-Type": "application/json"}

    async def _dispatch(self, method: str, path: str,
                        headers: Dict[str, str], body: bytes):
        bare, _, query = path.partition("?")
        reserved = self._reserved_handler(bare.rstrip("/"))
        if reserved is not None and method in ("GET", "HEAD"):
            # HEAD gets an empty body — the generic writer emits whatever
            # body we return, and body bytes after a HEAD reply desync
            # the keep-alive connection
            status, hbody, hheaders = reserved(query, headers)
            return status, (b"" if method == "HEAD" else hbody), hheaders
        api = self._route(path)
        if api is None:
            return 404, b'{"error": "no API registered at this path"}', {}
        if not self.health.ready:                      # draining: shed new
            return (503, b'{"error": "server draining"}',
                    self._shed_headers())
        req = ServingRequest(id=uuid.uuid4().hex, method=method, path=path,
                             headers=headers, body=body,
                             trace_id=headers.get(TRACE_HEADER),
                             tenant=headers.get(TENANT_HEADER, "default"))
        ex = api.submit(req)
        if ex is None:                                 # backpressure
            return (503, b'{"error": "serving queue saturated"}',
                    self._shed_headers())
        try:
            rep = await self._await_reply(api, ex)
        except asyncio.TimeoutError:
            return 504, b'{"error": "serving pipeline timeout"}', {}
        if rep is None:
            return 500, b'{"error": "empty reply"}', {}
        return rep.status, rep.body, dict(rep.headers)

    # -- API registry (HTTPSourceV2 ServiceInfo analogue) ------------------
    def register_api(self, path: str, max_queue: int = 1024,
                     reply_timeout_s: float = 30.0) -> ApiHandle:
        path = path.rstrip("/") or "/"
        with self._apis_lock:
            if path in self._apis:
                return self._apis[path]
            handle = ApiHandle(path, max_queue, reply_timeout_s)
            self._apis[path] = handle
            return handle

    def _route(self, request_path: str) -> Optional[ApiHandle]:
        """Longest registered prefix wins ("/a/b" before "/a")."""
        with self._apis_lock:
            best = None
            for path, handle in self._apis.items():
                if path == "/" or request_path == path \
                        or request_path.startswith(path + "/") \
                        or request_path.startswith(path + "?"):
                    if best is None or len(path) > len(best.path):
                        best = handle
            return best

    @property
    def address(self) -> Tuple[str, int]:
        return self._addr

    @property
    def url(self) -> str:
        h, p = self.address
        return f"http://{h}:{p}{'' if self.api_path == '/' else self.api_path}"

    def url_for(self, path: str) -> str:
        h, p = self.address
        path = path.rstrip("/") or "/"
        return f"http://{h}:{p}{'' if path == '/' else path}"

    # -- default-API passthrough (original one-endpoint surface) -----------
    def get_batch(self, max_rows: int = 64,
                  timeout_s: float = 0.05) -> List[ServingRequest]:
        return self._default.get_batch(max_rows, timeout_s)

    def reply(self, request_id: str, reply: ServingReply) -> bool:
        # request ids are unique across APIs; try the owning handle first
        if self._default.reply(request_id, reply):
            return True
        with self._apis_lock:
            handles = list(self._apis.values())
        return any(h.reply(request_id, reply) for h in handles
                   if h is not self._default)

    #: drain must observe queues+inflight idle for this long before
    #: closing — covers request bytes in transit that have not reached
    #: dispatch yet (sampling a single idle instant would close under
    #: them; a starved event loop can sit on unread requests for well
    #: over 100 ms, so the window is generous).  A request that still
    #: races the close gets a prompt connection-close — a retryable
    #: transport error, which HTTPClient's policy absorbs.
    _DRAIN_SETTLE_S = 0.2

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown: immediately stop accepting NEW connections
        (listener closed) and shed new requests/frames on existing ones
        (503 + ``Retry-After``; readyz → 503), wait until every ACCEPTED
        exchange has been answered and written back (queues empty,
        pending maps empty, no reply mid-write — held for a settle
        window), then close.

        Returns True when fully drained, False when ``timeout_s`` expired
        with work still in flight (the listener closes either way — a
        drain must terminate)."""
        self.health.begin_drain()

        def _stop_listener():
            if self._aserver is not None:
                self._aserver.close()
        try:
            self._loop.call_soon_threadsafe(_stop_listener)
        except RuntimeError:
            pass                         # loop already gone
        deadline = time.monotonic() + max(0.0, timeout_s)
        drained = False
        quiet_since: Optional[float] = None
        while True:
            now = time.monotonic()
            if self._queue_depth() == 0 and self._inflight == 0:
                if quiet_since is None:
                    quiet_since = now
                elif now - quiet_since >= self._DRAIN_SETTLE_S:
                    drained = True
                    break
            else:
                quiet_since = None
            if now >= deadline:
                break
            time.sleep(0.005)
        self.health.finish_drain()
        self.close()
        return drained

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.health.mark_closed()

        def _stop():
            if self._aserver is not None:
                self._aserver.close()
            tasks = [t for t in asyncio.all_tasks(self._loop)
                     if t is not asyncio.current_task(self._loop)]
            for task in tasks:
                task.cancel()

            async def _finish():
                # let the cancellations unwind BEFORE stopping the loop:
                # each handler's finally closes its transport, so racing
                # clients see a prompt connection-close instead of a
                # socket that leaks open until process exit (observed as
                # full client-side timeouts).  Bounded: a handler parked
                # in run_in_executor (a blocked streaming generator)
                # cannot be interrupted by cancel — stop the loop anyway
                # after the wait instead of hanging close() on it
                if tasks:
                    await asyncio.wait(tasks, timeout=2.0)
                self._loop.stop()
            asyncio.ensure_future(_finish(), loop=self._loop)
        try:
            self._loop.call_soon_threadsafe(_stop)
        except RuntimeError:      # loop already gone (failed start)
            return
        self._thread.join(timeout=5)


def _reply_never_raises(api: ApiHandle, request_id: str,
                        rep: ServingReply) -> bool:
    """``api.reply`` that cannot kill a serving worker thread: after
    drain/close the asyncio loop is gone and call_soon_threadsafe
    raises — the exchange is already lost either way, the loop must
    live.  Shared by ``_ApiLoop`` and ``_DecodeLoop``."""
    try:
        return api.reply(request_id, rep)
    except Exception:  # noqa: BLE001 — serving must not die
        return False


class _BatchAlignmentError(RuntimeError):
    """Model output rows cannot be mapped back onto requests (row count
    changed with no provenance) — a deployment bug, not poison data, so
    it must NOT enter the bisection path."""


class _ApiLoop:
    """One API's continuous loop: batch → transform → reply.

    Row-level fault isolation (the serving face of
    :mod:`synapseml_tpu.resilience.rowguard`):

    - a record whose ``input_parser`` throws answers 400 for ITSELF;
      the rest of the batch proceeds;
    - a poison record that makes ``transform`` throw is isolated by
      recursive batch halving and answers 500 for itself — clean
      records in the same micro-batch still get their 200s;
    - an XLA ``RESOURCE_EXHAUSTED`` halves the batch and retries both
      halves; the safe size is remembered (``rowguard_safe_batch_size``
      gauge) and caps every later micro-batch pull, so one oversized
      burst degrades throughput instead of killing the loop.
    """

    def __init__(self, server: ServingServer, api: ApiHandle,
                 model: Transformer,
                 input_parser: Callable[[ServingRequest], Dict[str, Any]],
                 output_col: str,
                 output_formatter: Callable[[Any], bytes],
                 batch_size: int, batch_timeout_s: float,
                 num_workers: int = 1,
                 max_queue_wait_s: Optional[float] = None):
        self.server = server
        self.api = api
        self.model = model
        self.input_parser = input_parser
        self.output_col = output_col
        self.output_formatter = output_formatter
        self.batch_size = batch_size
        self.batch_timeout_s = batch_timeout_s
        #: bound on time a request may sit queued before being shed with
        #: 503 — under overload the tail stays bounded instead of every
        #: request slowly timing out (None: no shedding)
        self.max_queue_wait_s = max_queue_wait_s
        reg = get_registry()
        self._m_records = reg.counter(
            "serving_records_total", "records replied 200", ("api",))
        self._m_rps = reg.gauge(
            "serving_records_per_sec",
            "last-batch records/sec through transform+reply", ("api",))
        self._m_batch = reg.histogram(
            "serving_batch_size", "records per micro-batch", ("api",),
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
        self._m_errors = reg.counter(
            "serving_errors_total", "batches failed (500) or shed (503)",
            ("api", "kind"))
        self._stop = threading.Event()
        #: >1 workers drain one queue concurrently: while one worker's
        #: transform holds the device/CPU (releasing the GIL), another
        #: batches and replies — opt-in, because concurrent transform
        #: calls require a thread-safe model (jitted models are)
        self._threads = [threading.Thread(target=self._loop, daemon=True)
                         for _ in range(max(1, num_workers))]
        for t in self._threads:
            t.start()

    @property
    def _oom_key(self) -> str:
        return f"serving:{self.api.path}"

    def _loop(self) -> None:
        from ..resilience.rowguard import safe_batch_size
        while not self._stop.is_set():
            pull = safe_batch_size(self._oom_key, self.batch_size)
            batch = self.api.get_batch(pull, self.batch_timeout_s)
            if not batch:
                continue
            if self.max_queue_wait_s is not None:
                now = time.monotonic()
                stale = [r for r in batch
                         if now - r.enqueued_at > self.max_queue_wait_s]
                if stale:
                    body = json.dumps({"error": "queue wait exceeded "
                                       f"{self.max_queue_wait_s}s"}).encode()
                    for req in stale:
                        self._safe_reply(req.id, ServingReply(503, body))
                    self._m_errors.inc(len(stale), api=self.api.path,
                                       kind="shed")
                    batch = [r for r in batch
                             if now - r.enqueued_at <= self.max_queue_wait_s]
                    if not batch:
                        continue
            # per-record parse: a malformed record 400s ITSELF only
            rows, good = [], []
            for req in batch:
                try:
                    rows.append(self.input_parser(req))
                    good.append(req)
                except Exception as e:  # noqa: BLE001 — isolated to record
                    self._m_errors.inc(1, api=self.api.path, kind="parse")
                    self._safe_reply(req.id, ServingReply(400, json.dumps(
                        {"error": f"unparseable record: {e}"}).encode()))
            if not good:
                continue
            t0 = time.perf_counter()
            served = self._transform_reply(good, rows)
            dt = time.perf_counter() - t0
            if served:
                self._m_records.inc(served, api=self.api.path)
                self._m_batch.observe(served, api=self.api.path)
                if dt > 0:
                    self._m_rps.set(served / dt, api=self.api.path)

    def _safe_reply(self, request_id: str, rep: ServingReply) -> bool:
        return _reply_never_raises(self.api, request_id, rep)

    def _reply_all(self, reqs: List[ServingRequest], status: int,
                   e: Exception, kind: str) -> None:
        self._m_errors.inc(len(reqs), api=self.api.path, kind=kind)
        body = json.dumps({"error": str(e)}).encode()
        for req in reqs:
            self._safe_reply(req.id, ServingReply(status, body))

    def _format_reply(self, req: ServingRequest, val: Any,
                      to_send: List) -> None:
        """Format one record's 200 (a formatter failure 500s only that
        record — formatting is per-record work, not batch work)."""
        try:
            body = self.output_formatter(val)
        except Exception as e:  # noqa: BLE001 — isolated to the record
            self._m_errors.inc(1, api=self.api.path, kind="format")
            to_send.append((req, ServingReply(500, json.dumps(
                {"error": f"output formatting failed: {e}"}).encode())))
            return
        to_send.append((req, ServingReply(
            200, body, {"Content-Type": "application/json"})))

    def _transform_reply(self, reqs: List[ServingRequest],
                         rows: List[Dict[str, Any]],
                         budget: Optional[List[int]] = None) -> int:
        """Transform + reply with row-level isolation; returns the number
        of records answered 200.  No reply leaves inside the try: a
        late exception after partial sends would otherwise re-answer
        already-answered records from the bisection path."""
        from ..resilience.faults import PreemptionError
        from ..resilience.rowguard import (is_oom_error, isolation_budget,
                                           oom_fault_point,
                                           record_safe_batch)
        if budget is None:
            # bounds isolation work for batch-INDEPENDENT failures (a
            # broken model fails both halves of every split): after the
            # shared budget the remaining batch 500s wholesale — the
            # pre-isolation behavior — instead of burning 2n-1
            # transforms on a model that was never going to answer
            budget = [isolation_budget(len(reqs))]
        budget[0] -= 1
        to_send: List[Tuple[ServingRequest, ServingReply]] = []
        rejected = 0
        try:
            oom_fault_point(self._oom_key, len(rows))
            ds = Dataset.from_rows(rows)
            out = self.model.transform(ds)
            col = out[self.output_col]
            if out.num_rows != len(reqs):
                # a guarded model (handleInvalid='skip'/'quarantine')
                # dropped poisoned rows: re-align replies through the
                # guard's source-row provenance — positional zip would
                # hand every later record its neighbor's prediction
                if not out.has_source_index:
                    raise _BatchAlignmentError(
                        f"model returned {out.num_rows} rows for "
                        f"{len(reqs)} records without row provenance; "
                        "replies cannot be aligned")
                idx = [int(p) for p in out.source_index]
                if (len(set(idx)) != len(idx)
                        or not all(0 <= p < len(reqs) for p in idx)):
                    # a row-EXPANDING model (Explode-style duplicate
                    # provenance) or foreign provenance: answering one
                    # request several times would race the exchange —
                    # fail loudly instead
                    raise _BatchAlignmentError(
                        "model output rows do not map 1:1 onto records "
                        "(duplicate or out-of-range source rows)")
                answered = set(idx)
                for pos, val in zip(idx, col):
                    self._format_reply(reqs[pos], val, to_send)
                body = json.dumps({"error": "record rejected by the "
                                   "model's handleInvalid policy"}).encode()
                for i, req in enumerate(reqs):
                    if i not in answered:
                        rejected += 1
                        to_send.append((req, ServingReply(422, body)))
            else:
                for req, val in zip(reqs, col):
                    self._format_reply(req, val, to_send)
        except PreemptionError as e:
            # control plane, never row-attributable (rowguard's
            # _NON_ROW_ERRORS contract): the process is being evicted —
            # shed the batch retryably instead of bisecting it
            self._reply_all(reqs, 503, e, "preempt")
            return 0
        except _BatchAlignmentError as e:
            self._reply_all(reqs, 500, e, "transform")
            return 0
        except Exception as e:  # noqa: BLE001 — serving must not die
            if getattr(e, "all_rows_invalid", False):
                # the model's OWN row guard rejected every record in
                # this (sub-)batch — that's a data verdict, not a model
                # failure: same 422 the provenance-aligned path answers
                self._reply_all(reqs, 422, e, "rejected")
                return 0
            oom = is_oom_error(e)
            if len(reqs) == 1 or (budget[0] <= 0 and not oom):
                self._reply_all(reqs, 500, e, "oom" if oom else "transform")
                return 0
            mid = len(reqs) // 2
            if oom:
                # batch-size failure: remember the size that fits so
                # later micro-batch pulls stay under it
                record_safe_batch(self._oom_key, max(1, mid))
                self._m_errors.inc(1, api=self.api.path, kind="oom")
            # halve either way: OOM retries smaller, a poison record is
            # cornered in O(log n) transforms while clean ones still
            # answer 200
            return (self._transform_reply(reqs[:mid], rows[:mid], budget)
                    + self._transform_reply(reqs[mid:], rows[mid:], budget))
        if rejected:
            self._m_errors.inc(rejected, api=self.api.path, kind="rejected")
        served = 0
        for req, rep in to_send:
            self._safe_reply(req.id, rep)
            if rep.status == 200:
                served += 1
        return served

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)


class _TokenStream:
    """Blocking token-chunk iterator bridging the decode loop and the
    chunked-transfer reply writer: the loop pushes encoded chunks as
    tokens are sampled, the listener's executor thread pulls them.  The
    exchange stays in-flight until ``finish()``'s sentinel drains, so
    ``drain()``'s zero-drop guarantee covers live token streams.

    ``abandon()`` is the listener's back-signal for a client that
    disconnected mid-stream: the decode loop checks the flag every
    tick and cancels the slot instead of decoding the full budget for
    nobody (the streaming counterpart of the non-stream reply-window
    expiry).  An abandoned stream drops further pushes so the queue
    cannot grow behind a dead connection."""

    _DONE = object()

    def __init__(self):
        self._q: "Queue" = Queue()
        self.abandoned = False

    def push(self, chunk: bytes) -> None:
        if not self.abandoned:
            self._q.put(chunk)

    def finish(self) -> None:
        self._q.put(self._DONE)

    def abandon(self) -> None:
        self.abandoned = True

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            raise StopIteration
        return item


@dataclass
class _DecodeSeq:
    """One request's decode lifecycle (queued → slotted → retired)."""
    req: ServingRequest
    ids: List[int]
    max_new: int
    stream: bool
    slot: Optional[int] = None
    tokens: List[int] = field(default_factory=list)
    stream_obj: Optional[_TokenStream] = None
    first_token_at: Optional[float] = None
    #: request-scoped trace id (None ⇒ not sampled — every trace call
    #: with a None id is a no-op)
    trace_id: Optional[str] = None
    #: the request was held in queue for an in-flight program compile
    #: at least once (the compile_wait trace event fires on the first
    #: hold only)
    compile_waited: bool = False
    #: conversation key for the session journal / affinity plane
    session: Optional[str] = None
    #: the sequence was rebuilt from a journal replay: ``ids`` is the
    #: journaled prompt + committed tokens, ``tokens`` pre-seeded with
    #: the committed tokens, ``max_new`` the REMAINING budget — the
    #: admit prefills the whole context and the continuation is
    #: token-exact with the interrupted turn
    resumed: bool = False
    #: the journal replay already holds the turn's FULL token budget
    #: (the crash landed after the last token commit but before the
    #: reply) — the replay IS the reply; admitting it would decode one
    #: token past the requested budget
    replay_complete: bool = False
    #: QoS tenant this sequence bills to (from the ``X-SML-Tenant``
    #: header or the ``tenant`` payload field)
    tenant: str = "default"
    #: per-request priority-class override (None ⇒ tenant policy)
    priority: Optional[int] = None
    #: preemption ticket from ``engine.preempt`` while parked — the
    #: sequence holds no slot and re-enters via ``engine.resume``
    ticket: Optional[Dict[str, Any]] = None
    #: the per-tenant rate budget was already charged for this request
    #: (charged once, at first admission consideration)
    budget_spent: bool = False
    #: disaggregated-prefill handoff outcome for this request (None ⇒
    #: no pool armed, or the handoff has not run yet — it runs at most
    #: once per request; see serving.disagg.HANDOFF_OUTCOMES)
    handoff_outcome: Optional[str] = None

    @property
    def remaining(self) -> int:
        """Tokens left in this sequence's budget (the preemption
        victim tie-break: longest-remaining is cheapest to set aside)."""
        return max(0, int(self.max_new) - len(self.tokens))


class _DecodeLoop:
    """Continuous-batching serving loop for an LLM decode engine —
    the token-streaming sibling of :class:`_ApiLoop`.

    Instead of batch → transform → reply, the loop runs one SLOTTED
    decode step at a time and re-schedules between steps:

    - **admission every step** — queued requests are pulled with the
      non-blocking :meth:`ApiHandle.poll` and admitted into free cache
      slots the moment one exists; a request never waits for a "full
      batch" and an in-flight batch never stalls waiting on arrivals;
    - **SLO-aware shedding** — with ``ttft_slo_s`` set, a queued request
      whose PROJECTED time-to-first-token (time already waited + the
      soonest slot release, from the engine's remaining-token floor ×
      the observed step time) exceeds the SLO answers 503 with the
      PR-2 queue-depth ``Retry-After`` hint instead of serving a stale
      reply — including while the server drains;
    - **eviction per step** — EOS / token-budget retirement frees the
      slot immediately for the next admission; a reply window that
      expired mid-decode cancels the slot;
    - **streaming** — ``stream`` requests are answered immediately with
      a chunked body fed token-by-token through the existing
      exchange/reply machinery (one JSON line per token, a final
      ``done`` line with the full ids).

    The engine is duck-typed (``admit``/``step``/``cancel``/
    ``n_slots``/``active_count``/``free_slot_count``/
    ``min_remaining_tokens``, plus optional
    ``tokens_per_step_estimate`` — a speculative engine's
    accepted-tokens-per-step EWMA, folded into the SLO projection —
    optional ``trace_sink``: when present and unset the loop
    installs its request-trace hook so the engine's per-slot
    decode/verify outcomes land on the request timelines — and the
    optional compile plane: ``admission_ready(prompt_len)`` holds a
    request whose program is still compiling in queue instead of
    admitting it into a stall, and ``compile_plane`` exempts the
    pre-ready warmup window from the SLO shed projection) so this
    module never imports jax; pass a
    :class:`synapseml_tpu.models.llm.SlotEngine`.  A ``step()`` may
    return SEVERAL events per slot (a speculative engine commits whole
    accepted spans); the loop streams each committed token in order.

    **Observability**: every request gets a ``trace_id`` at admission
    into the plane (or adopts the propagated ``X-SML-Trace-Id``) and a
    sampled per-request timeline — queued → shed/admitted →
    prefill(bucket) → decode/verify steps → retired/cancelled/expired
    — in the process :class:`~synapseml_tpu.telemetry.tracing.
    RequestTraceStore` (served at ``GET /tracez``); TTFT, per-token
    latency, occupancy, and admission/shed/retirement counts
    additionally feed the windowed SLO plane
    (:mod:`synapseml_tpu.telemetry.slo`, served at ``GET /sloz``) with
    ``ttft_slo_s``/``token_slo_s`` as its declared objectives.
    """

    def __init__(self, server: ServingServer, api: ApiHandle, engine: Any,
                 input_parser: Callable[[ServingRequest], Dict[str, Any]],
                 output_formatter: Optional[
                     Callable[[List[int]], Dict[str, Any]]] = None,
                 max_new_tokens_default: int = 32,
                 ttft_slo_s: Optional[float] = None,
                 token_slo_s: Optional[float] = None,
                 idle_timeout_s: float = 0.02,
                 trace_sample_every: Optional[int] = None,
                 request_tracer=None, slo_window=None, journal=None,
                 qos=None, max_tenants: int = 256, disagg=None):
        self.server = server
        self.api = api
        self.engine = engine
        #: optional session journal (duck-typed on the
        #: :class:`~synapseml_tpu.models.llm.kvtier.SessionJournal`
        #: surface — ``begin``/``append_tokens``/``retire``/``replay``
        #: + a public ``metrics``/``name``): every committed token is
        #: journaled fsync-first, and a ``resume`` request replays the
        #: journal so a killed replica's conversation continues
        #: token-exactly on this one
        self.journal = journal
        self.input_parser = input_parser
        self.output_formatter = output_formatter or (
            lambda ids: {"ids": [int(t) for t in ids]})
        self.max_new_tokens_default = int(max_new_tokens_default)
        self.ttft_slo_s = ttft_slo_s
        self.token_slo_s = token_slo_s
        self.idle_timeout_s = idle_timeout_s
        #: the multi-tenant scheduling policy: weighted-fair admission
        #: order, per-tenant rate budgets, and preemption verdicts all
        #: come from here (jax-free; a default scheduler treats every
        #: tenant equally, so single-tenant traffic behaves exactly as
        #: the old FIFO did)
        from .qos import DEFAULT_TENANT, OVERFLOW_TENANT, QosScheduler
        self._overflow_tenant = OVERFLOW_TENANT
        self.qos = qos if qos is not None else QosScheduler()
        #: cardinality bound on CLIENT-MINTED tenant ids: every distinct
        #: tenant permanently materialises an SLO plane, metric label
        #: sets, and QoS deficit/budget state — all unauthenticated
        #: client-controlled, so without a cap a client cycling random
        #: ids grows server memory and /sloz payloads without bound.
        #: Tenants with a registered TenantPolicy always get their own
        #: plane; dynamic (unregistered) ids are granted planes up to
        #: this cap and rejected 429 past it.
        self.max_tenants = max(1, int(max_tenants))
        self._tenant_ids = {DEFAULT_TENANT}
        self._waiting: List[_DecodeSeq] = []
        #: preempted sequences holding a resume ticket instead of a
        #: slot — auto-resumed token-exactly once pressure clears
        self._parked: List[_DecodeSeq] = []
        self._by_slot: Dict[int, _DecodeSeq] = {}
        # duck-typed engine/journal compatibility: only thread tenant
        # kwargs through surfaces that declare them (test fakes and
        # older engines keep working untouched)
        import inspect
        def _takes_tenant(fn) -> bool:
            try:
                return "tenant" in inspect.signature(fn).parameters
            except (TypeError, ValueError):
                return False
        self._engine_tenant_kw = _takes_tenant(
            getattr(engine, "admit", lambda: None))
        self._journal_tenant_kw = journal is not None and _takes_tenant(
            getattr(journal, "begin", lambda: None))
        self._step_ewma: Optional[float] = None
        self._retired_window: List[float] = []
        # request-scoped tracing: the process store by default (so the
        # listener's /tracez sees this loop's requests); the sampling
        # knob adjusts THAT store (process-wide — /tracez is one surface)
        self._tracer = request_tracer or get_request_tracer()
        if trace_sample_every is not None:
            self._tracer.sample_every = max(0, int(trace_sample_every))
        # the engine reports per-slot step outcomes (decode/verify with
        # span sizes) through its optional trace_sink hook; only claim
        # an unset one — a caller-installed sink wins
        if getattr(engine, "trace_sink", "absent") is None:
            engine.trace_sink = self._engine_trace
        # windowed SLO plane (served at /sloz): one plane per API path
        self._slo = slo_window or get_slo_store().window(api.path)
        if ttft_slo_s is not None:
            self._slo.set_objective("ttft", float(ttft_slo_s))
        if token_slo_s is not None:
            self._slo.set_objective("token_latency", float(token_slo_s))
        #: lazily-created per-tenant attribution planes (named
        #: ``<api>@tenant=<id>``; filtered by ``/sloz?tenant=``) — fed
        #: alongside the aggregate plane so a noisy tenant cannot hide
        #: inside aggregate percentiles.  Occupancy is engine-wide, not
        #: per-tenant, so tenant planes never observe it (their null
        #: occupancy is skipped by the autoscaler reduction).
        self._tenant_windows: Dict[str, Any] = {}
        #: disaggregated prefill pool (duck-typed on serving.disagg.
        #: PrefillPool: ``handoff(ids, session=, tenant=) -> outcome``):
        #: when armed, every fresh request's prompt is offered to the
        #: pool before admission — an ``ok`` handoff lands its K/V in
        #: this engine's host arena so the admit warm-restores it; any
        #: other outcome just means the admit prefills locally.  The
        #: decode phase gets its own ``@phase=decode`` SLO plane so the
        #: two pools scale independently.
        self.disagg = disagg
        self._phase_slo = None
        if disagg is not None:
            from ..telemetry.slo import phase_plane_name
            self._phase_slo = get_slo_store().window(
                phase_plane_name(api.path, "decode"))
            if ttft_slo_s is not None:
                self._phase_slo.set_objective("ttft", float(ttft_slo_s))
            if token_slo_s is not None:
                self._phase_slo.set_objective("token_latency",
                                              float(token_slo_s))
        self._slo_export_at = 0.0
        reg = get_registry()
        self._m_ttft = reg.histogram(
            "llm_ttft_seconds", "request arrival to first generated token",
            ("api",), buckets=SERVING_TTFT_BUCKETS)
        self._m_tok_lat = reg.histogram(
            "llm_token_latency_seconds",
            "per-token decode latency (one observation per emitted token)",
            ("api",), buckets=SERVING_TOKEN_LATENCY_BUCKETS)
        self._m_tokens = reg.counter(
            "llm_tokens_total", "tokens streamed/replied by the decode "
            "loop", ("api",))
        self._m_sheds = reg.counter(
            "llm_sheds_total", "requests shed by the decode loop",
            ("api", "reason", "tenant"))
        self._m_preempt = reg.counter(
            "llm_qos_preemptions_total", "slots preempted by the QoS "
            "plane for a higher priority class", ("api", "tenant"))
        self._m_errors = reg.counter(
            "serving_errors_total", "batches failed (500) or shed (503)",
            ("api", "kind"))
        self._m_records = reg.counter(
            "serving_records_total", "records replied 200", ("api",))
        self._m_rps = reg.gauge(
            "serving_records_per_sec",
            "last-batch records/sec through transform+reply", ("api",))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- shared with _ApiLoop ---------------------------------------------
    def _safe_reply(self, request_id: str, rep: ServingReply) -> bool:
        return _reply_never_raises(self.api, request_id, rep)

    # -- request-scoped tracing -------------------------------------------
    def _engine_trace(self, slot: int, name: str, **attrs) -> None:
        """The engine's ``trace_sink``: map the slot back to its
        sequence and append the step event to the request timeline
        (cancelled-under-us slots and unsampled requests no-op)."""
        seq = self._by_slot.get(slot)
        if seq is not None and seq.trace_id is not None:
            self._tracer.event(seq.trace_id, name, slot=slot, **attrs)

    @staticmethod
    def _trace_headers(seq: _DecodeSeq) -> Dict[str, str]:
        """Reply header echoing the request's trace id (sampled
        requests only) — lets a client/balancer stitch the hop chain."""
        if seq.trace_id is None:
            return {}
        return {TRACE_HEADER_CANONICAL: seq.trace_id}

    # -- admission ---------------------------------------------------------
    def _pump_queue(self) -> None:
        """Move newly-arrived requests into the waiting list.  Blocks
        only when the loop is otherwise idle.  The pull is sized to
        FILL the waiting list up to its cap — ``max(2·n_slots,
        max_queue)`` — rather than a few slots' worth, because QoS
        admission (priority tiers, weighted-fair order, tenant
        budgets) can only reorder what it has seen: a small fixed pull
        would leave a high-priority tenant head-of-line-blocked in the
        raw FIFO behind a flooding neighbor's burst.  Crucially the
        pull is the cap MINUS the backlog already held
        (waiting + parked): once the backlog reaches the cap the pump
        stops draining, the api queue fills, and enqueue-time 503
        backpressure fires — without the subtraction a sustained flood
        would be drained into ``_waiting`` every tick and accumulate
        there without bound while the queue-full 503 never tripped."""
        cap = max(2 * self.engine.n_slots,
                  getattr(self.api, "max_queue", 1024))
        room = max(0, cap - len(self._waiting) - len(self._parked))
        if room == 0:
            return
        if self.engine.active_count or self._waiting:
            batch = self.api.poll(room)
        else:
            batch = self.api.get_batch(room, self.idle_timeout_s)
        for req in batch:
            try:
                spec = self.input_parser(req)
                ids = [int(t) for t in spec.get("ids", [])]
                session = spec.get("session")
                resume = bool(spec.get("resume", False)) \
                    and session is not None and self.journal is not None
                if not ids and not resume:
                    raise ValueError("empty prompt")
                max_new = int(spec.get("max_new_tokens",
                                       self.max_new_tokens_default))
                # payload wins over the X-SML-Tenant header (a gateway
                # may inject the header; an authenticated body field is
                # more specific); absent both ⇒ the default tenant
                tenant = str(spec.get("tenant") or req.tenant or "default")
                if len(tenant) > 256:
                    # a tenant id is a namespace key (journals, arena,
                    # affinity) — an arbitrarily long one is abuse, and
                    # truncating would silently merge two namespaces
                    raise ValueError("tenant id exceeds 256 chars")
                prio = spec.get("priority", req.priority)
                prio = int(prio) if prio is not None else None
            except Exception as e:  # noqa: BLE001 — isolated to record
                self._m_errors.inc(1, api=self.api.path, kind="parse")
                self._safe_reply(req.id, ServingReply(400, json.dumps(
                    {"error": f"unparseable record: {e}"}).encode()))
                continue
            if not self._tenant_admitted(tenant):
                # dynamic-tenant cardinality cap: tenant ids are
                # client-controlled and each distinct one permanently
                # allocates an SLO plane, metric labels, and QoS state
                # — past the cap an unregistered id is rejected, under
                # the bounded overflow label so the rejection itself
                # cannot be used to grow cardinality either
                self._m_sheds.inc(1, api=self.api.path,
                                  reason="tenant_cap",
                                  tenant=self._overflow_tenant)
                self._m_errors.inc(1, api=self.api.path, kind="shed")
                self._slo.count("shed")
                self._safe_reply(req.id, ServingReply(429, json.dumps(
                    {"error": "tenant plane limit reached: register a "
                     "TenantPolicy for this tenant or raise "
                     "max_tenants"}).encode()))
                continue
            seq = _DecodeSeq(req, ids, max_new,
                             bool(spec.get("stream", False)),
                             tenant=tenant, priority=prio)
            if session is not None:
                seq.session = str(session)
            if resume:
                self._try_resume(seq)
                if not seq.ids:
                    # replay found nothing usable and the request
                    # carried no prompt of its own: there is nothing
                    # token-exact OR cold to serve
                    self._m_errors.inc(1, api=self.api.path, kind="parse")
                    self._safe_reply(req.id, ServingReply(
                        404, json.dumps(
                            {"error": "resume: no journaled state for "
                             "session"}).encode()))
                    continue
                if seq.replay_complete:
                    payload = self.output_formatter(seq.tokens)
                    self._safe_reply(req.id, ServingReply(
                        200, json.dumps(payload).encode(),
                        {"Content-Type": "application/json"}))
                    self._m_records.inc(1, api=self.api.path)
                    continue
            # trace minted here (admission into the serving plane) or
            # adopted from the upstream hop (always sampled: a
            # propagated request is never half-traced)
            seq.trace_id = self._tracer.begin(req.trace_id,
                                              api=self.api.path)
            self._tracer.event(seq.trace_id, "queued",
                               prompt_tokens=len(ids), max_new=max_new,
                               stream=seq.stream)
            self._waiting.append(seq)

    def _try_resume(self, seq: _DecodeSeq) -> None:
        """Rebuild an interrupted conversation from the session journal
        (the crash-failover path: the router repinned this session here
        after its replica died, or this replica relaunched).  On a
        usable replay the sequence becomes journaled-prompt + committed
        tokens with the REMAINING budget — prefill reproduces the dead
        replica's state exactly, so the continuation is token-exact.
        Every degraded outcome (no journal file, corrupt/truncated
        state) is counted and the request falls back to its own ids —
        a cold start, never a wrong token."""
        m = self.journal.metrics
        name = getattr(self.journal, "name", "llm")
        try:
            # tenant-namespaced replay: the journal both hashes the
            # tenant into the file path and refuses a state recorded
            # under another tenant, so a cross-tenant session-id
            # collision reads as a miss (→ 404), never as tenant B's
            # committed tokens
            st = (self.journal.replay(seq.session, tenant=seq.tenant)
                  if self._journal_tenant_kw
                  else self.journal.replay(seq.session))
        except Exception:  # noqa: BLE001 — degraded, never fatal
            st = None
        if st is None or not (st.prompt or st.committed):
            m.restores.inc(1, engine=name, source="journal",
                           outcome="miss")
            return
        if st.truncated:
            # the size cap dropped oldest tokens: the journal holds a
            # SUFFIX, and replaying a suffix is not token-exact
            m.restores.inc(1, engine=name, source="journal",
                           outcome="truncated")
            return
        committed = [int(t) for t in st.committed]
        seq.ids = [int(t) for t in st.prompt] + committed
        seq.tokens = list(committed)
        remaining = int(st.max_new) - len(committed)
        if remaining <= 0:
            # every budgeted token was journaled before the crash —
            # the turn finished, only the reply was lost
            seq.replay_complete = True
        seq.max_new = max(1, remaining)
        seq.resumed = True
        m.restores.inc(1, engine=name, source="journal", outcome="ok")
        _flight_record("kvtier_session_resume", api=self.api.path,
                       session=seq.session, committed=len(committed),
                       remaining=seq.max_new)

    def _journal_safe(self, fn) -> None:
        """Run one journal operation without ever failing the serving
        path — a full disk or unlinked root loses durability (flight-
        recorded), not the conversation.  An armed ``kill`` fault
        SIGKILLs inside ``fn`` before this frame can catch anything,
        which is exactly the crash the journal protects against."""
        try:
            fn()
        except Exception as exc:  # noqa: BLE001 — serving must not die
            _flight_record("kvtier_journal_error", api=self.api.path,
                           error=repr(exc))

    def _queue_waited(self, seq: _DecodeSeq) -> float:
        """Seconds this request has spent as REAL queue pressure.
        Warmup/compile time is not queue pressure: while the engine's
        compile plane is still warming, the whole wait is exempt (a
        cold replica would otherwise project absurd TTFTs and shed its
        entire first wave the moment warmup gating lands), and once it
        is warm the clock starts at plane-ready time for requests that
        arrived during the warm — not at their enqueue time."""
        anchor = seq.req.enqueued_at
        plane = getattr(self.engine, "compile_plane", None)
        if plane is not None:
            if not plane.is_warm:
                return 0.0
            ready_at = plane.ready_at
            if ready_at is not None and ready_at > anchor:
                anchor = ready_at
        return time.monotonic() - anchor

    def _projected_ttft(self, seq: _DecodeSeq, position: int) -> float:
        """Projection of this request's TTFT if admitted as soon as
        capacity allows: time already queued plus the soonest slot
        release, scaled by how many queued requests are ahead of it.

        The release estimate is the SMALLER of the engine's
        remaining-token floor × observed step time (exact when
        sequences run their full budget) and the observed
        inter-retirement interval from the recent window (the honest
        estimate when EOS retires sequences far under budget —
        budget-based projection alone would shed requests that real
        retirement traffic was about to serve).  A SPECULATIVE engine
        advances each slot by its accepted span, so the floor divides
        by the engine's accepted-tokens-per-step estimate
        (``tokens_per_step_estimate``, optional in the duck-type
        contract): remaining-tokens ÷ accepted-tokens-per-step steps
        remain, not remaining-tokens steps — without this the
        projection over-sheds by the whole speculative speedup."""
        waited = self._queue_waited(seq)
        if self.engine.free_slot_count > 0:
            return waited
        rem = self.engine.min_remaining_tokens()
        if rem is None or self._step_ewma is None:
            return waited
        tps_fn = getattr(self.engine, "tokens_per_step_estimate", None)
        tps = max(1.0, float(tps_fn())) if tps_fn is not None else 1.0
        next_free = rem / tps * self._step_ewma
        now = time.monotonic()
        recent = [t for t in self._retired_window if now - t < 5.0]
        if recent:
            next_free = min(next_free, 5.0 / len(recent))
        waves = 1 + position // max(1, self.engine.n_slots)
        return waited + next_free * waves

    def _shed_headers(self) -> Dict[str, str]:
        from ..resilience.health import retry_after_from_depth
        depth = len(self._waiting) + self.engine.active_count
        now = time.monotonic()
        self._retired_window = [t for t in self._retired_window
                                if now - t < 5.0]
        rps = len(self._retired_window) / 5.0
        return {"Retry-After": str(retry_after_from_depth(depth, rps))}

    def _tenant_admitted(self, tenant: str) -> bool:
        """Bound the universe of tenant ids this plane materialises
        state for: always the default tenant and every tenant with a
        registered :class:`TenantPolicy`; dynamic (client-minted) ids
        are granted a plane first-come up to ``max_tenants`` and
        rejected past it — an unauthenticated client cycling random
        ids cannot grow the SLO store, metric label sets, or QoS
        ledgers without bound."""
        if tenant in self._tenant_ids:
            return True
        registered = getattr(self.qos, "is_registered", None)
        if ((registered is not None and registered(tenant))
                or len(self._tenant_ids) < self.max_tenants):
            self._tenant_ids.add(tenant)
            return True
        return False

    def _tenant_slo(self, tenant: str):
        """Get-or-create the per-tenant attribution plane (same
        objectives as the aggregate plane, so burn rate is comparable
        per tenant)."""
        w = self._tenant_windows.get(tenant)
        if w is None:
            from ..telemetry.slo import tenant_plane_name
            w = get_slo_store().window(
                tenant_plane_name(self.api.path, tenant))
            if self.ttft_slo_s is not None:
                w.set_objective("ttft", float(self.ttft_slo_s))
            if self.token_slo_s is not None:
                w.set_objective("token_latency", float(self.token_slo_s))
            self._tenant_windows[tenant] = w
        return w

    def _shed(self, seq: _DecodeSeq, reason: str) -> None:
        self._m_sheds.inc(1, api=self.api.path, reason=reason,
                          tenant=seq.tenant)
        self._m_errors.inc(1, api=self.api.path, kind="shed")
        self._slo.count("shed")
        self._tenant_slo(seq.tenant).count("shed")
        if self._phase_slo is not None:
            self._phase_slo.count("shed")
        self._tracer.event(seq.trace_id, "shed", reason=reason)
        self._tracer.finish(seq.trace_id, "shed")
        self._safe_reply(seq.req.id, ServingReply(
            503, json.dumps({"error": "projected time-to-first-token "
                             "exceeds the serving SLO"}).encode(),
            {**self._shed_headers(), **self._trace_headers(seq)}))

    def _shed_budget(self, seq: _DecodeSeq, retry_after_s: float) -> None:
        """Per-tenant rate-budget shed: 429 with the budget's own
        refill horizon as ``Retry-After`` — the throttled tenant gets
        an honest backoff hint, every other tenant is untouched."""
        self._m_sheds.inc(1, api=self.api.path, reason="budget",
                          tenant=seq.tenant)
        self._m_errors.inc(1, api=self.api.path, kind="shed")
        self._slo.count("shed")
        self._tenant_slo(seq.tenant).count("shed")
        if self._phase_slo is not None:
            self._phase_slo.count("shed")
        self._tracer.event(seq.trace_id, "shed", reason="budget")
        self._tracer.finish(seq.trace_id, "shed")
        self._safe_reply(seq.req.id, ServingReply(
            429, json.dumps({"error": "tenant over rate budget"}).encode(),
            {"Retry-After": str(max(1, int(math.ceil(retry_after_s)))),
             **self._trace_headers(seq)}))

    def _admit_waiting(self) -> None:
        keep: List[_DecodeSeq] = []
        ready_fn = getattr(self.engine, "admission_ready", None)
        # per-tenant rate budgets first (charged ONCE per request, in
        # tokens = the requested budget, through the PR-2 token-bucket
        # RetryBudget): an over-budget tenant sheds 429 with its own
        # refill horizon while every other tenant is untouched
        pool: List[_DecodeSeq] = list(self._parked)
        self._parked = []
        for seq in self._waiting:
            if not seq.budget_spent:
                seq.budget_spent = True
                ok, retry_after = self.qos.shed_verdict(
                    seq.tenant, float(seq.max_new))
                if not ok:
                    self._shed_budget(seq, retry_after)
                    continue
            pool.append(seq)
        # weighted-fair admission order: strict priority tiers, token-
        # weighted deficit round robin across tenants within a tier
        # (parked preempted sequences compete through the same order)
        starved: List[_DecodeSeq] = []
        for pos, seq in enumerate(self.qos.admission_order(pool)):
            if seq.ticket is not None:
                # preempted earlier: re-enter through engine.resume —
                # restore + continue is token-exact (the PR 17 kvtier
                # ticket contract), so pressure clearing auto-resumes
                # the victim with zero wrong tokens
                slot = (self.engine.resume(seq.ticket)
                        if self.engine.free_slot_count > 0 else None)
                if slot is None:
                    starved.append(seq)
                    keep.append(seq)
                    continue
                seq.ticket = None
                seq.slot = slot
                self._by_slot[slot] = seq
                self._tracer.event(seq.trace_id, "resumed", slot=slot)
                continue
            if ready_fn is not None and not ready_fn(len(seq.ids)):
                # a program this admission needs is still compiling
                # (the compile plane bumped it to the front of the
                # lattice): hold the request in queue — the decode
                # loop keeps stepping already-warm buckets, and
                # _queue_waited exempts the wait from SLO shedding
                if not seq.compile_waited:
                    seq.compile_waited = True
                    self._tracer.event(seq.trace_id, "compile_wait",
                                       prompt_tokens=len(seq.ids))
                keep.append(seq)
                continue
            if (self.ttft_slo_s is not None
                    and self._projected_ttft(seq, pos) > self.ttft_slo_s):
                self._shed(seq, "slo")
                continue
            if self.engine.free_slot_count == 0:
                starved.append(seq)
                keep.append(seq)
                continue
            if (self.disagg is not None and seq.handoff_outcome is None
                    and not seq.resumed):
                # disaggregated prefill: offer the prompt to the pool
                # FIRST (at most once per request).  handoff() never
                # raises — every failure mode is an attributed outcome
                # — and an "ok" lands the K/V in this engine's arena so
                # the admit below warm-restores it token-exactly; any
                # other outcome means the admit prefills locally (the
                # colocated fallback, never a wrong token).  Resumed
                # turns skip the pool: the journal failover path owns
                # their context reconstruction.
                try:
                    seq.handoff_outcome = self.disagg.handoff(
                        seq.ids, session=seq.session, tenant=seq.tenant)
                except Exception:  # noqa: BLE001 — belt over the contract
                    seq.handoff_outcome = "fallback"
                    _flight_record("disagg_handoff", api=self.api.path,
                                   outcome="fallback", error=True)
                self._tracer.event(seq.trace_id, "disagg_handoff",
                                   outcome=seq.handoff_outcome)
            try:
                res = (self.engine.admit(seq.ids, seq.max_new,
                                         tenant=seq.tenant)
                       if self._engine_tenant_kw
                       else self.engine.admit(seq.ids, seq.max_new))
            except ValueError as e:             # prompt cannot fit
                self._m_errors.inc(1, api=self.api.path, kind="parse")
                self._tracer.finish(seq.trace_id, "error", error=str(e))
                self._safe_reply(seq.req.id, ServingReply(
                    400, json.dumps({"error": str(e)}).encode()))
                continue
            if res is None:                     # raced full — requeue
                starved.append(seq)
                keep.append(seq)
                continue
            seq.slot = res.slot
            seq.first_token_at = time.monotonic()
            ttft = seq.first_token_at - seq.req.enqueued_at
            self._m_ttft.observe(ttft, api=self.api.path)
            self._slo.observe_ttft(ttft)
            self._slo.count("admitted")
            tslo = self._tenant_slo(seq.tenant)
            tslo.observe_ttft(ttft)
            tslo.count("admitted")
            if self._phase_slo is not None:
                self._phase_slo.observe_ttft(ttft)
                self._phase_slo.count("admitted")
            self._tracer.event(
                seq.trace_id, "admitted", slot=res.slot,
                reused_tokens=getattr(res, "reused_tokens", 0))
            self._tracer.event(seq.trace_id, "prefill", slot=res.slot,
                               bucket=getattr(res, "bucket", 0))
            if seq.stream:
                seq.stream_obj = _TokenStream()
                if not self._safe_reply(seq.req.id, ServingReply(
                        200, seq.stream_obj,
                        {"Content-Type": "application/json",
                         **self._trace_headers(seq)})):
                    self.engine.cancel(res.slot)
                    # the reply window expired before admission: close
                    # the timeline like every other termination path —
                    # /tracez must not show this request live forever
                    self._tracer.finish(seq.trace_id, "expired")
                    continue
            self._by_slot[res.slot] = seq
            if self.journal is not None and seq.session is not None:
                # (re)baseline the journal BEFORE the first token lands:
                # for a resumed turn ids already embeds the committed
                # tokens, so a SECOND crash replays prompt' = prompt +
                # committed and stays token-exact
                self._journal_safe(lambda s=seq: self.journal.begin(
                    s.session, s.ids, s.max_new, tenant=s.tenant)
                    if self._journal_tenant_kw else
                    self.journal.begin(s.session, s.ids, s.max_new))
            self._on_token(seq, res.token, res.finished,
                           getattr(res, "reason", None))
        self._waiting = [s for s in keep if s.ticket is None]
        self._parked = [s for s in keep if s.ticket is not None]
        self._maybe_preempt(starved)

    def _maybe_preempt(self, starved: List[_DecodeSeq]) -> None:
        """Preemption policy: when capacity-starved demand includes a
        STRICTLY higher priority class than some active slot, evict the
        lowest-priority longest-remaining slot through the engine's
        ticket path (``preempt``/``resume``, PR 17) and park it — the
        freed slot serves the higher class next tick and the victim
        auto-resumes token-exactly when pressure clears.  Every verdict
        is flight-recorded with the justifying pressure snapshot."""
        if not starved or self.engine.free_slot_count > 0:
            return
        preempt_fn = getattr(self.engine, "preempt", None)
        if preempt_fn is None or not self._by_slot:
            return
        demand = max(self.qos.priority_of(s) for s in starved)
        victim = self.qos.preemption_victim(
            demand, list(self._by_slot.values()))
        if victim is None:
            return
        # snapshot the JUSTIFYING state before the eviction mutates it
        # (preempt frees the slot, so free_slots would read post-hoc)
        snap = self.qos.pressure_snapshot(starved,
                                          self.engine.free_slot_count)
        ticket = preempt_fn(victim.slot)
        if ticket is None:
            # the engine declined (slot raced to retirement, arena
            # full): the verdict never happened — committing it here
            # would overcount preemptions and burn the anti-thrash
            # cooldown, delaying the next legitimate eviction
            return
        self.qos.commit_preemption()
        self._by_slot.pop(victim.slot, None)
        victim.ticket = ticket
        victim.slot = None
        self._parked.append(victim)
        self._m_preempt.inc(1, api=self.api.path, tenant=victim.tenant)
        self._tracer.event(victim.trace_id, "preempted",
                           demand_priority=demand)
        _flight_record("qos_preemption", api=self.api.path,
                       tenant=victim.tenant,
                       victim_priority=self.qos.priority_of(victim),
                       demand_priority=demand,
                       victim_remaining=victim.remaining,
                       pressure=snap)

    # -- token/retirement handling ----------------------------------------
    def _on_token(self, seq: _DecodeSeq, token: int, finished: bool,
                  reason: Optional[str] = None) -> None:
        if self.journal is not None and seq.session is not None:
            # journal BEFORE the client sees the token: a token the
            # client received must survive a SIGKILL one instruction
            # later (the append is fsync'd)
            self._journal_safe(lambda s=seq, t=token:
                               self.journal.append_tokens(
                                   s.session, [int(t)], tenant=s.tenant)
                               if self._journal_tenant_kw else
                               self.journal.append_tokens(s.session,
                                                          [int(t)]))
        seq.tokens.append(int(token))
        self._m_tokens.inc(1, api=self.api.path)
        if seq.stream_obj is not None:
            seq.stream_obj.push(
                json.dumps({"token": int(token)}).encode() + b"\n")
        if finished:
            self._finish(seq, reason)

    def _finish(self, seq: _DecodeSeq,
                reason: Optional[str] = None) -> None:
        self._by_slot.pop(seq.slot, None)
        now = time.monotonic()
        # prune at the append site: the window must stay ~5s of
        # timestamps, not one float per request served since startup
        self._retired_window = [t for t in self._retired_window
                                if now - t < 5.0]
        self._retired_window.append(now)
        self._slo.count("retired")
        self._tenant_slo(seq.tenant).count("retired")
        if self._phase_slo is not None:
            self._phase_slo.count("retired")
        self._tracer.event(seq.trace_id, "retired",
                           tokens=len(seq.tokens), reason=reason)
        self._tracer.finish(seq.trace_id, "retired",
                            tokens=len(seq.tokens), reason=reason)
        if self.journal is not None and seq.session is not None:
            # compaction at retirement: the session's append history
            # collapses to one state record (bounded file), kept on
            # disk — it is the failover source for the NEXT turn and
            # for a relaunch
            self._journal_safe(lambda s=seq:
                               self.journal.retire(s.session,
                                                   tenant=s.tenant)
                               if self._journal_tenant_kw else
                               self.journal.retire(s.session))
        payload = self.output_formatter(seq.tokens)
        if seq.stream_obj is not None:
            payload["done"] = True
            seq.stream_obj.push(json.dumps(payload).encode() + b"\n")
            seq.stream_obj.finish()
            self._m_records.inc(1, api=self.api.path)
        else:
            ok = self._safe_reply(seq.req.id, ServingReply(
                200, json.dumps(payload).encode(),
                {"Content-Type": "application/json",
                 **self._trace_headers(seq)}))
            if ok:
                self._m_records.inc(1, api=self.api.path)

    def _cancel_expired(self) -> None:
        """A sequence nobody is waiting on must not hold a slot (and
        SLO-shed queued requests on its behalf): a NON-STREAM request
        whose reply window expired (the listener answered 504 and
        forgot the exchange), or a STREAM whose client disconnected
        mid-decode (the chunk writer flagged the stream abandoned).
        Streams replied at admission, so the window applies only to
        non-stream sequences."""
        now = time.monotonic()
        for slot, seq in list(self._by_slot.items()):
            if seq.stream_obj is not None:
                dead = seq.stream_obj.abandoned
                kind = "disconnect"
            else:
                dead = (now - seq.req.enqueued_at
                        > self.api.reply_timeout_s)
                kind = "expired"
            if dead:
                self.engine.cancel(slot)
                self._by_slot.pop(slot, None)
                self._m_errors.inc(1, api=self.api.path, kind=kind)
                self._tracer.event(seq.trace_id, "cancelled", reason=kind)
                self._tracer.finish(seq.trace_id, kind,
                                    tokens=len(seq.tokens))
        # a PARKED (preempted) sequence holds no slot but still owns a
        # reply window/stream — the same expiry rules drop its ticket
        live_parked: List[_DecodeSeq] = []
        for seq in self._parked:
            if seq.stream_obj is not None:
                dead = seq.stream_obj.abandoned
                kind = "disconnect"
            else:
                dead = (now - seq.req.enqueued_at
                        > self.api.reply_timeout_s)
                kind = "expired"
            if dead:
                self._m_errors.inc(1, api=self.api.path, kind=kind)
                self._tracer.event(seq.trace_id, "cancelled", reason=kind)
                self._tracer.finish(seq.trace_id, kind,
                                    tokens=len(seq.tokens))
            else:
                live_parked.append(seq)
        self._parked = live_parked
        # a WAITING request past its reply window is dead weight: the
        # listener already answered 504 and forgot the exchange, so
        # admitting it would decode tokens nobody can receive (and
        # SLO-shed live requests queued behind it).  Streams have no
        # window here — a waiting stream has not been replied yet, so
        # the same expiry applies.
        live_waiting: List[_DecodeSeq] = []
        for seq in self._waiting:
            if now - seq.req.enqueued_at > self.api.reply_timeout_s:
                self._m_errors.inc(1, api=self.api.path, kind="expired")
                self._tracer.event(seq.trace_id, "cancelled",
                                   reason="expired")
                self._tracer.finish(seq.trace_id, "expired", tokens=0)
            else:
                live_waiting.append(seq)
        self._waiting = live_waiting

    # -- the loop ----------------------------------------------------------
    def _loop(self) -> None:
        # the _ApiLoop invariant — serving must not die — holds here
        # too: any engine failure (XLA resource errors, a duck-typed
        # engine bug) fails the IN-FLIGHT sequences with 500s, frees
        # their slots, and keeps the thread serving
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — serving must not die
                self._fail_inflight(e)
                time.sleep(0.05)    # a persistently-broken engine must
                #                     not spin the loop hot

    def _tick(self) -> None:
        self._pump_queue()
        self._admit_waiting()
        self._cancel_expired()
        self._export_slo()
        if not self.engine.active_count:
            return
        t0 = time.perf_counter()
        events = self.engine.step()
        dt = time.perf_counter() - t0
        self._step_ewma = (dt if self._step_ewma is None
                           else 0.8 * self._step_ewma + 0.2 * dt)
        # a speculative engine commits a SPAN per slot per step: the
        # per-token latency observation is the step time amortized
        # over the slot's committed span (observing the full dt once
        # per token would overcount it span-fold and read as spec
        # WORSENING token latency when it improved it)
        span: Dict[int, int] = {}
        for ev in events:
            span[ev.slot] = span.get(ev.slot, 0) + 1
        for ev in events:
            seq = self._by_slot.get(ev.slot)
            if seq is None:         # cancelled under us
                continue
            tok_s = dt / span[ev.slot]
            self._m_tok_lat.observe(tok_s, api=self.api.path)
            self._slo.observe_token_latency(tok_s)
            self._tenant_slo(seq.tenant).observe_token_latency(tok_s)
            if self._phase_slo is not None:
                self._phase_slo.observe_token_latency(tok_s)
            # the DRR deficit is charged by COMMITTED tokens, one per
            # step event — a speculative engine commits several per
            # slot per step, so token-weighting (not request-counting)
            # is what keeps the fair shares honest under spec decode
            self.qos.charge(seq.tenant, 1)
            self._on_token(seq, ev.token, ev.finished, ev.reason)
        if events and dt > 0:
            self._m_rps.set(len(events) / dt, api=self.api.path)

    def _export_slo(self) -> None:
        """Refresh the plane's /metrics gauges from the windows on a
        ~1 s cadence.  Occupancy is sampled HERE — time-uniformly,
        idle ticks included — not per decode step: per-step sampling
        only ever sees busy instants, so a plane idle 59 s of every 60
        would read ~1.0 occupancy and the autoscaler consuming /sloz
        ("shrink on idle occupancy") would never scale it down."""
        now = time.monotonic()
        if now - self._slo_export_at >= 1.0:
            self._slo_export_at = now
            self._slo.observe_occupancy(
                self.engine.active_count / max(1, self.engine.n_slots))
            self._slo.export_gauges()
            if self._phase_slo is not None:
                # the decode phase's occupancy IS this engine's slots —
                # the prefill pool samples its own plane per handoff
                self._phase_slo.observe_occupancy(
                    self.engine.active_count / max(1, self.engine.n_slots))
                self._phase_slo.export_gauges()
            for w in self._tenant_windows.values():
                w.export_gauges()

    def _fail_inflight(self, e: Exception) -> None:
        """Answer every in-flight sequence 500 (streams get a final
        error line) and free its slot after an engine failure.
        PARKED (preempted) sequences are in flight too — their resume
        tickets reference engine/arena state the failure (and the
        recovery reset below) invalidates, so they get the same 500
        instead of hanging un-notified until their reply window
        expires on a persistently-broken engine."""
        body = json.dumps({"error": str(e)}).encode()
        for slot, seq in list(self._by_slot.items()):
            try:
                self.engine.cancel(slot)
            except Exception:  # noqa: BLE001 — engine may be broken
                pass
            self._fail_seq(seq, e, body)
            self._by_slot.pop(slot, None)
        for seq in self._parked:
            self._fail_seq(seq, e, body)
        self._parked = []
        self._m_errors.inc(1, api=self.api.path, kind="transform")
        # the engine's jitted programs donate their cache buffers: an
        # exception mid-call can leave the cache pointing at DELETED
        # arrays, so without a rebuild every later admit/step fails
        # forever ("Array has been deleted") — recovery, not cleanup
        reset = getattr(self.engine, "reset", None)
        if reset is not None:
            try:
                reset()
            except Exception:  # noqa: BLE001 — stay alive regardless
                pass

    def _fail_seq(self, seq: _DecodeSeq, e: Exception,
                  body: bytes) -> None:
        """Terminate one in-flight sequence with the engine error
        (final stream line or a 500 reply) and close its timeline."""
        if seq.stream_obj is not None:
            seq.stream_obj.push(json.dumps(
                {"error": str(e)}).encode() + b"\n")
            seq.stream_obj.finish()
        else:
            self._safe_reply(seq.req.id, ServingReply(500, body))
        self._tracer.finish(seq.trace_id, "error", error=str(e))

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)
        # release every still-open stream: the listener's executor
        # thread is parked in Queue.get() on it, and an unfinished
        # stream would leak that (non-daemon) thread past close —
        # observed as a process that never exits.  After the join the
        # loop thread is gone, so this cannot race a push.
        for seq in list(self._by_slot.values()) + self._parked:
            if seq.stream_obj is not None:
                seq.stream_obj.finish()
        self._by_slot.clear()
        self._parked.clear()


def _default_format(value: Any) -> bytes:
    if isinstance(value, np.ndarray):
        value = value.tolist()
    elif isinstance(value, (np.generic,)):
        value = value.item()
    return json.dumps({"prediction": value}).encode()


class PipelineServer:
    """Continuous serving loop for ONE model: requests → Dataset →
    ``model.transform`` → replies (the ``readStream.continuousServer()``
    pipeline of reference §3.5 collapsed into one object)."""

    def __init__(self, model: Transformer,
                 input_parser: Callable[[ServingRequest], Dict[str, Any]],
                 output_col: str = "prediction",
                 output_formatter: Optional[Callable[[Any], bytes]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", batch_size: int = 64,
                 batch_timeout_s: float = 0.01, max_queue: int = 1024,
                 num_workers: int = 1,
                 max_queue_wait_s: Optional[float] = None):
        self.model = model
        self.server = ServingServer(host, port, api_path,
                                    max_queue=max_queue)
        self._loop = _ApiLoop(self.server, self.server._default, model,
                              input_parser, output_col,
                              output_formatter or _default_format,
                              batch_size, batch_timeout_s,
                              num_workers=num_workers,
                              max_queue_wait_s=max_queue_wait_s)

    _default_format = staticmethod(_default_format)

    @property
    def url(self) -> str:
        return self.server.url

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown: the serving loop keeps replying while the
        server sheds new work and flushes accepted exchanges, THEN the
        loop stops (stopping it first would deadlock the flush)."""
        drained = self.server.drain(timeout_s)
        self._loop.stop()
        return drained

    def close(self) -> None:
        self._loop.stop()
        self.server.close()


class MultiPipelineServer:
    """Several named pipelines on ONE server — request paths route to the
    API whose pipeline should serve them (reference: multiple named APIs
    with per-executor shared servers, HTTPSourceV2.scala:47-90,
    DistributedHTTPSource.scala:203).

    ``apis``: {path: spec} where spec is a dict with keys ``model``,
    ``input_parser`` and optional ``output_col``/``output_formatter``/
    ``batch_size``/``batch_timeout_s``/``max_queue``.
    """

    def __init__(self, apis: Dict[str, Dict[str, Any]],
                 host: str = "127.0.0.1", port: int = 0):
        if not apis:
            raise ValueError("MultiPipelineServer needs at least one API")
        first = next(iter(apis))
        self.server = ServingServer(
            host, port, api_path=first,
            max_queue=int(apis[first].get("max_queue", 1024)))
        self._loops: List[_ApiLoop] = []
        for path, spec in apis.items():
            handle = self.server.register_api(
                path, max_queue=int(spec.get("max_queue", 1024)))
            self._loops.append(_ApiLoop(
                self.server, handle, spec["model"], spec["input_parser"],
                spec.get("output_col", "prediction"),
                spec.get("output_formatter") or _default_format,
                int(spec.get("batch_size", 64)),
                float(spec.get("batch_timeout_s", 0.01)),
                num_workers=int(spec.get("num_workers", 1)),
                max_queue_wait_s=spec.get("max_queue_wait_s")))

    def url_for(self, path: str) -> str:
        return self.server.url_for(path)

    def drain(self, timeout_s: float = 30.0) -> bool:
        drained = self.server.drain(timeout_s)
        for loop in self._loops:
            loop.stop()
        return drained

    def close(self) -> None:
        for loop in self._loops:
            loop.stop()
        self.server.close()
