"""Evaluation plots: confusion matrix and ROC curve.

TPU-native counterpart of the reference's pyspark plotting helpers
(reference: core/src/main/python/synapse/ml/plot/plot.py:18,56).  The
metric computation is pure numpy (no sklearn) and always returned, so the
functions work headless; rendering happens only when matplotlib is
importable and ``show`` is not disabled.

Accepts a :class:`synapseml_tpu.Dataset`, a pandas DataFrame, or any
mapping of column name → array.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from .core.dataset import Dataset

__all__ = ["confusion_matrix", "roc_curve", "confusionMatrix", "roc"]


def _columns(df: Any, *cols: str) -> Tuple[np.ndarray, ...]:
    return tuple(np.asarray(df[c]) for c in cols)


def confusion_matrix(df: Any, y_col: str, y_hat_col: str,
                     labels: Sequence[Any],
                     plot: bool = True) -> Dict[str, Any]:
    """Counts[i, j] = rows with true label ``labels[i]`` predicted ``labels[j]``.

    Returns {"matrix", "normalized", "accuracy"}; additionally renders a
    heatmap if matplotlib is available and ``plot`` is True.
    """
    y, y_hat = _columns(df, y_col, y_hat_col)
    labels = list(labels)
    index = {lab: i for i, lab in enumerate(labels)}
    k = len(labels)
    cm = np.zeros((k, k), dtype=np.int64)
    for t, p in zip(y, y_hat):
        ti, pi = index.get(t), index.get(p)
        if ti is not None and pi is not None:
            cm[ti, pi] += 1
    row_sums = np.maximum(cm.sum(axis=1, keepdims=True), 1)
    cmn = cm.astype(np.float64) / row_sums
    # accuracy over the rows the matrix counts, so trace/sum is consistent
    accuracy = float(np.trace(cm)) / max(int(cm.sum()), 1)
    result = {"matrix": cm, "normalized": cmn, "accuracy": accuracy}
    if plot:
        _render_confusion(cm, cmn, labels, accuracy)
    return result


def _render_confusion(cm, cmn, labels, accuracy) -> None:
    try:
        import matplotlib.pyplot as plt
    except Exception:
        return
    tick_marks = np.arange(len(labels))
    plt.text(-0.3, -0.55, f"Accuracy = {round(accuracy * 100, 1)}%",
             fontsize=18)
    plt.xticks(tick_marks, labels, rotation=0)
    plt.yticks(tick_marks, labels, rotation=90)
    plt.imshow(cmn, interpolation="nearest", cmap="Blues", vmin=0, vmax=1)
    for i in range(cm.shape[0]):
        for j in range(cm.shape[1]):
            plt.text(j, i, cm[i, j], horizontalalignment="center",
                     fontsize=18,
                     color="white" if cmn[i, j] > 0.1 else "black")
    plt.colorbar()
    plt.xlabel("Predicted Label", fontsize=18)
    plt.ylabel("True Label", fontsize=18)


def roc_curve(df: Any, y_col: str, y_hat_col: str, thresh: float = 0.5,
              plot: bool = True) -> Dict[str, np.ndarray]:
    """ROC of score column ``y_hat_col`` against binarized ``y_col``.

    True labels are binarized at ``thresh`` (mirroring the reference's
    ``f2i``); the score column is swept over every distinct value.
    Returns {"fpr", "tpr", "thresholds", "auc"}.
    """
    y_raw, scores = _columns(df, y_col, y_hat_col)
    y = (np.asarray(y_raw, dtype=np.float64) > thresh).astype(np.int64)
    scores = np.asarray(scores, dtype=np.float64)

    order = np.argsort(-scores, kind="stable")
    y_sorted, s_sorted = y[order], scores[order]
    # cut only where the score changes so tied scores share one point
    distinct = np.where(np.diff(s_sorted))[0]
    cuts = np.r_[distinct, y.size - 1]
    tps = np.cumsum(y_sorted)[cuts].astype(np.float64)
    fps = (cuts + 1) - tps
    n_pos = max(float(y.sum()), 1.0)
    n_neg = max(float(y.size - y.sum()), 1.0)
    fpr = np.r_[0.0, fps / n_neg]
    tpr = np.r_[0.0, tps / n_pos]
    thresholds = np.r_[np.inf, s_sorted[cuts]]
    # scalar AUC via the shared rank-statistic helper (one implementation
    # package-wide; the curve above is only for rendering)
    from .models.gbdt.metrics import auc as _auc
    auc = _auc(y, scores)
    if plot:
        try:
            import matplotlib.pyplot as plt
            plt.plot(fpr, tpr)
            plt.xlabel("False Positive Rate", fontsize=20)
            plt.ylabel("True Positive Rate", fontsize=20)
        except Exception:
            pass
    return {"fpr": fpr, "tpr": tpr, "thresholds": thresholds, "auc": auc}


#: reference-compatible camelCase aliases (plot.py:18,56)
confusionMatrix = confusion_matrix
roc = roc_curve
