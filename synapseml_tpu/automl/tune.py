"""TuneHyperparameters / FindBestModel.

Re-designs the reference's thread-pooled random search (reference:
core/.../automl/TuneHyperparameters.scala:38-150 — ExecutorService with
``parallelism`` threads, each fitting one param map and evaluating
accuracy-style metrics on a random train/test split) and FindBestModel
(automl/FindBestModel.scala).  Trials run in a thread pool here too:
each fit is dominated by jitted device work, which releases the GIL, so
host threads overlap compile/dispatch while the TPU serializes the math.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.dataset import Dataset
from ..core.params import (FloatParam, IntParam, PyObjectParam, StringParam)
from ..core.pipeline import Estimator, Evaluator, Model
from ..ops.train import MetricConstants, roc_auc
from .space import GridSpace, RandomSpace


def _score(metric: str, ds: Dataset, label_col: str, pred_col: str,
           scores_col: Optional[str]) -> float:
    y = np.asarray(ds[label_col], np.float64)
    if metric == MetricConstants.AUC:
        if scores_col and scores_col in ds:
            sc = ds[scores_col]
            s = (np.stack([np.asarray(v, np.float64) for v in sc])[:, -1]
                 if sc.dtype == object else sc.astype(np.float64))
        else:
            s = np.asarray(ds[pred_col], np.float64)
        return roc_auc(y, s)
    p = np.asarray(ds[pred_col], np.float64)
    if metric == MetricConstants.ACCURACY:
        return float((p == y).mean())
    if metric == MetricConstants.PRECISION:
        tp = float(((p > 0) & (y > 0)).sum())
        return tp / max(float((p > 0).sum()), 1.0)
    if metric == MetricConstants.RECALL:
        tp = float(((p > 0) & (y > 0)).sum())
        return tp / max(float((y > 0).sum()), 1.0)
    if metric == MetricConstants.MSE:
        return float(((p - y) ** 2).mean())
    if metric == MetricConstants.RMSE:
        return float(np.sqrt(((p - y) ** 2).mean()))
    if metric == MetricConstants.MAE:
        return float(np.abs(p - y).mean())
    if metric == MetricConstants.R2:
        ss_res = float(((p - y) ** 2).sum())
        ss_tot = float(((y - y.mean()) ** 2).sum()) or 1.0
        return 1.0 - ss_res / ss_tot
    raise ValueError(f"unknown metric {metric}")


def _larger_better(metric: str) -> bool:
    return metric not in (MetricConstants.MSE, MetricConstants.RMSE,
                          MetricConstants.MAE)


class TuneHyperparameters(Estimator):
    """Parallel random/grid hyperparameter search
    (reference: TuneHyperparameters.scala:38)."""

    models = PyObjectParam(doc="candidate estimators (param-map stages "
                           "reference these instances)")
    evaluationMetric = StringParam(doc="metric name", default="accuracy")
    paramSpace = PyObjectParam(doc="GridSpace or RandomSpace")
    numRuns = IntParam(doc="trials for RandomSpace", default=10)
    parallelism = IntParam(doc="concurrent fits", default=4)
    seed = IntParam(doc="train/test split seed", default=0)
    trainRatio = FloatParam(doc="train fraction", default=0.75)
    labelCol = StringParam(doc="label column", default="label")
    predictionCol = StringParam(doc="prediction column", default="prediction")
    scoresCol = StringParam(doc="probability/raw column for AUC",
                            default="probability")
    evaluator = PyObjectParam(doc="optional Evaluator overriding the metric")

    def _fit(self, ds: Dataset) -> "TuneHyperparametersModel":
        space = self.get("paramSpace")
        if space is None:
            raise ValueError("paramSpace is required")
        if isinstance(space, RandomSpace):
            maps = list(space.param_maps(int(self.numRuns)))
        else:
            maps = list(space.param_maps())
        # candidates in `models` with no paramSpace entry still compete,
        # fitted once with their declared defaults (the reference sweeps
        # every model in `models`)
        referenced = {id(stage) for pm in maps for stage, _, _ in pm}
        for est in (self.get("models") or []):
            if id(est) not in referenced:
                maps.append([(est, None, None)])
        train, test = ds.random_split([self.trainRatio,
                                       1 - self.trainRatio],
                                      seed=int(self.seed))
        metric = self.evaluationMetric
        ev: Optional[Evaluator] = self.get("evaluator")

        def one_trial(pm: List[Tuple[Any, str, Any]]):
            # group assignments by estimator instance, clone, apply
            by_stage: Dict[int, Any] = {}
            assign: Dict[int, List[Tuple[str, Any]]] = {}
            for stage, name, val in pm:
                by_stage.setdefault(id(stage), stage)
                assign.setdefault(id(stage), [])
                if name is not None:  # (est, None, None) = defaults trial
                    assign[id(stage)].append((name, val))
            results = []
            for sid, stage in by_stage.items():
                clone = stage.copy()
                for name, val in assign[sid]:
                    clone.set(name, val)
                model = clone.fit(train)
                scored = model.transform(test)
                if ev is not None:
                    m = ev.evaluate(scored)
                else:
                    m = _score(metric, scored, self.labelCol,
                               self.predictionCol, self.scoresCol)
                results.append((m, model, assign[sid]))
            return results

        all_results = []
        workers = max(1, int(self.parallelism))
        if workers == 1:
            for pm in maps:
                all_results.extend(one_trial(pm))
        else:
            with ThreadPoolExecutor(max_workers=workers) as pool:
                for res in pool.map(one_trial, maps):
                    all_results.extend(res)
        if not all_results:
            raise ValueError("empty parameter space")
        larger = (ev.is_larger_better() if ev is not None
                  else _larger_better(metric))
        key = (lambda t: t[0]) if larger else (lambda t: -t[0])
        best_metric, best_model, best_assign = max(all_results, key=key)

        out = TuneHyperparametersModel()
        out.set("bestModel", best_model)
        out.set("bestMetric", float(best_metric))
        out.set("allMetrics", [float(m) for m, _, _ in all_results])
        out.set("bestParams", {name: val for name, val in best_assign})
        return out


class TuneHyperparametersModel(Model):
    bestModel = PyObjectParam(doc="winning fitted model")
    bestMetric = PyObjectParam(doc="winning metric value")
    allMetrics = PyObjectParam(doc="metric per trial")
    bestParams = PyObjectParam(doc="winning param assignment")

    def _transform(self, ds: Dataset) -> Dataset:
        return self.get("bestModel").transform(ds)


class FindBestModel(Estimator):
    """Evaluate already-fitted models on a dataset and keep the best
    (reference: automl/FindBestModel.scala)."""

    models = PyObjectParam(doc="fitted Transformer candidates")
    evaluationMetric = StringParam(doc="metric name", default="accuracy")
    labelCol = StringParam(doc="label column", default="label")
    predictionCol = StringParam(doc="prediction column", default="prediction")
    scoresCol = StringParam(doc="probability column for AUC",
                            default="probability")

    def _fit(self, ds: Dataset) -> "BestModel":
        models = self.get("models")
        if not models:
            raise ValueError("models is required")
        metric = self.evaluationMetric
        scored_metrics = []
        for m in models:
            scored = m.transform(ds)
            scored_metrics.append(_score(metric, scored, self.labelCol,
                                         self.predictionCol, self.scoresCol))
        larger = _larger_better(metric)
        best_i = int(np.argmax(scored_metrics) if larger
                     else np.argmin(scored_metrics))
        out = BestModel()
        out.set("bestModel", models[best_i])
        out.set("bestModelMetrics", float(scored_metrics[best_i]))
        out.set("allModelMetrics", [float(m) for m in scored_metrics])
        return out


class BestModel(Model):
    bestModel = PyObjectParam(doc="winning fitted model")
    bestModelMetrics = PyObjectParam(doc="winning metric value")
    allModelMetrics = PyObjectParam(doc="metric per candidate")

    def _transform(self, ds: Dataset) -> Dataset:
        return self.get("bestModel").transform(ds)
