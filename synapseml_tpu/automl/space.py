"""Hyperparameter spaces (reference: core/.../automl/HyperparamBuilder.scala,
DefaultHyperparams.scala): discrete / range distributions per param, swept
as a full grid or random draws."""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


class DiscreteHyperParam:
    """Finite set of values (reference: DiscreteHyperParam)."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def grid_values(self) -> List[Any]:
        return list(self.values)

    def sample(self, rng) -> Any:
        return self.values[int(rng.integers(0, len(self.values)))]


class RangeHyperParam:
    """Closed numeric range (reference: RangeHyperParam); ``log=True``
    samples log-uniformly; int ranges produce ints."""

    def __init__(self, low, high, log: bool = False, n_grid: int = 5):
        if high <= low:
            raise ValueError("high must exceed low")
        self.low, self.high = low, high
        self.log = log
        self.n_grid = n_grid
        self.is_int = isinstance(low, int) and isinstance(high, int)

    def grid_values(self) -> List[Any]:
        if self.log:
            pts = np.exp(np.linspace(np.log(self.low), np.log(self.high),
                                     self.n_grid))
        else:
            pts = np.linspace(self.low, self.high, self.n_grid)
        if self.is_int:
            return sorted({int(round(p)) for p in pts})
        return [float(p) for p in pts]

    def sample(self, rng) -> Any:
        if self.log:
            v = float(np.exp(rng.uniform(np.log(self.low),
                                         np.log(self.high))))
        else:
            v = float(rng.uniform(self.low, self.high))
        return int(round(v)) if self.is_int else v


class HyperparamBuilder:
    """Accumulates (estimator, paramName) -> distribution entries
    (reference: HyperparamBuilder.addHyperparam)."""

    def __init__(self):
        self._entries: List[Tuple[Any, str, Any]] = []

    def add_hyperparam(self, stage, param_name: str, dist) -> "HyperparamBuilder":
        stage.get_param(param_name)  # validate existence early
        self._entries.append((stage, param_name, dist))
        return self

    def build(self) -> List[Tuple[Any, str, Any]]:
        return list(self._entries)


class GridSpace:
    """Cartesian product of every distribution's grid values
    (reference: GridSpace)."""

    def __init__(self, entries: List[Tuple[Any, str, Any]]):
        self.entries = entries

    def param_maps(self) -> Iterator[List[Tuple[Any, str, Any]]]:
        grids = [d.grid_values() for _, _, d in self.entries]
        for combo in itertools.product(*grids):
            yield [(stage, name, val) for (stage, name, _), val
                   in zip(self.entries, combo)]


class RandomSpace:
    """Random draws from each distribution (reference: RandomSpace)."""

    def __init__(self, entries: List[Tuple[Any, str, Any]], seed: int = 0):
        self.entries = entries
        self.seed = seed

    def param_maps(self, n: int) -> Iterator[List[Tuple[Any, str, Any]]]:
        rng = np.random.default_rng(self.seed)
        for _ in range(n):
            yield [(stage, name, d.sample(rng))
                   for stage, name, d in self.entries]


class DefaultHyperparams:
    """Sensible default search ranges per estimator family (reference:
    automl/DefaultHyperparams.scala:18-60 — per-learner
    ``defaultRange`` tables consumed by TuneHyperparameters)."""

    @staticmethod
    def gbdt(stage) -> List[Tuple[Any, str, Any]]:
        return (HyperparamBuilder()
                .add_hyperparam(stage, "numIterations",
                                RangeHyperParam(20, 100, n_grid=3))
                .add_hyperparam(stage, "learningRate",
                                RangeHyperParam(0.01, 0.3, log=True,
                                                n_grid=3))
                .add_hyperparam(stage, "numLeaves",
                                DiscreteHyperParam([15, 31, 63]))
                .add_hyperparam(stage, "lambdaL2",
                                RangeHyperParam(0.0, 1.0, n_grid=3))
                .build())

    @staticmethod
    def online_sgd(stage) -> List[Tuple[Any, str, Any]]:
        return (HyperparamBuilder()
                .add_hyperparam(stage, "learningRate",
                                RangeHyperParam(0.05, 2.0, log=True,
                                                n_grid=4))
                .add_hyperparam(stage, "l2",
                                DiscreteHyperParam([0.0, 1e-6, 1e-4]))
                .add_hyperparam(stage, "numPasses",
                                DiscreteHyperParam([1, 3, 5]))
                .build())

    @staticmethod
    def for_stage(stage) -> List[Tuple[Any, str, Any]]:
        """Dispatch by available params, mirroring the reference's
        per-learner overloads."""
        names = {p.name for p in stage.params}
        if "numLeaves" in names:
            return DefaultHyperparams.gbdt(stage)
        if "numPasses" in names:
            return DefaultHyperparams.online_sgd(stage)
        raise ValueError(
            f"no default hyperparam table for {type(stage).__name__}")
