"""Hyperparameter search (reference: core/.../automl/)."""

from .space import (DiscreteHyperParam, GridSpace, HyperparamBuilder,
                    RandomSpace, RangeHyperParam)
from .tune import (BestModel, FindBestModel, TuneHyperparameters,
                   TuneHyperparametersModel)

__all__ = [
    "DiscreteHyperParam", "GridSpace", "HyperparamBuilder", "RandomSpace",
    "RangeHyperParam", "BestModel", "FindBestModel", "TuneHyperparameters",
    "TuneHyperparametersModel",
]
