"""Hyperparameter search (reference: core/.../automl/)."""

from .space import (DefaultHyperparams, DiscreteHyperParam, GridSpace,
                    HyperparamBuilder, RandomSpace, RangeHyperParam)
from .tune import (BestModel, FindBestModel, TuneHyperparameters,
                   TuneHyperparametersModel)

__all__ = [
    "DefaultHyperparams", "DiscreteHyperParam", "GridSpace",
    "HyperparamBuilder", "RandomSpace", "RangeHyperParam", "BestModel",
    "FindBestModel", "TuneHyperparameters", "TuneHyperparametersModel",
]
