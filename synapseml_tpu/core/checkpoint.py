"""Step-level training checkpoints.

The reference persists *models* (native model strings / ComplexParams,
reference: org/apache/spark/ml/ComplexParamsSerializer.scala,
booster/LightGBMBooster.scala:272-284) but has NO mid-training step
checkpointing — a failed job restarts the stage (SURVEY §5.3/§5.4).
This build adds orbax-style step checkpoints for its jit train loops:

- a checkpoint = any pytree of arrays (TrainState params/opt_state/...),
  flattened to one ``.npz`` plus a pickled treedef side-car;
- writes are ATOMIC (tmp dir + ``os.replace``) so a killed process never
  leaves a half-written step visible;
- ``max_to_keep`` pruning, ``latest_step`` discovery, and
  ``restore`` into a like-structured template (donated arrays get fresh
  host buffers, then the caller re-shards onto its mesh).
"""

from __future__ import annotations

import os
import pickle
import re
import shutil
import tempfile
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..resilience.faults import get_faults

_STEP_RE = re.compile(r"^step_(\d{10})$")


def _is_array_leaf(x) -> bool:
    return isinstance(x, (np.ndarray, np.generic)) or hasattr(x, "dtype")


class CheckpointManager:
    """Directory of ``step_<n>`` checkpoints with atomic writes."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = directory
        self.max_to_keep = max_to_keep
        os.makedirs(directory, exist_ok=True)

    # -- discovery ---------------------------------------------------------
    def all_steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.exists(os.path.join(self.directory, name,
                                                 "arrays.npz")):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    # -- save --------------------------------------------------------------
    def save(self, step: int, pytree: Any,
             metrics: Optional[Dict[str, float]] = None) -> str:
        leaves, treedef = jax.tree_util.tree_flatten(pytree)
        arrays = {}
        others = {}
        for i, leaf in enumerate(leaves):
            if _is_array_leaf(leaf):
                arrays[f"leaf_{i}"] = np.asarray(leaf)
            else:
                others[i] = leaf
        # treedefs with unpicklable statics (optax closures, bound apply
        # fns) fall back to positional restore via restore_state_dict
        try:
            treedef_bytes = pickle.dumps(treedef)
            others_bytes = pickle.dumps(others)
        except Exception:
            treedef_bytes, others_bytes = None, None
            if others:
                raise TypeError(
                    "pytree mixes non-array leaves with an unpicklable "
                    "treedef; cannot checkpoint")
        final = self._step_dir(step)
        tmp = tempfile.mkdtemp(prefix=".tmp_ckpt_", dir=self.directory)
        try:
            np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
            # SIGKILL here must leave only the tmp dir (invisible to
            # discovery) — the fault site that proves the atomicity claim
            get_faults().kill_point("checkpoint.save.pre_publish",
                                    step=step)
            with open(os.path.join(tmp, "structure.pkl"), "wb") as f:
                pickle.dump({"treedef_bytes": treedef_bytes,
                             "others_bytes": others_bytes,
                             "n_leaves": len(leaves),
                             "metrics": dict(metrics or {})}, f)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(tmp, final)          # atomic publish
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        from ..telemetry.flight import record as flight_record
        flight_record("checkpoint", step=int(step), path=final)
        get_faults().kill_point("checkpoint.save.post_publish", step=step)
        self._prune()
        return final

    def _prune(self) -> None:
        steps = self.all_steps()
        while self.max_to_keep and len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            shutil.rmtree(self._step_dir(victim), ignore_errors=True)

    # -- restore -----------------------------------------------------------
    def _load(self, step: Optional[int]):
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}")
        d = self._step_dir(step)
        with open(os.path.join(d, "structure.pkl"), "rb") as f:
            meta = pickle.load(f)
        leaves: List[Any] = [None] * meta["n_leaves"]
        with np.load(os.path.join(d, "arrays.npz"), allow_pickle=False) as z:
            for key in z.files:
                leaves[int(key.split("_", 1)[1])] = z[key]
        if meta.get("others_bytes"):
            for i, val in pickle.loads(meta["others_bytes"]).items():
                leaves[i] = val
        return leaves, meta

    def restore(self, step: Optional[int] = None) -> Any:
        leaves, meta = self._load(step)
        if meta.get("treedef_bytes") is None:
            raise TypeError(
                "checkpoint was saved without a picklable treedef; restore "
                "with restore_state_dict(template)")
        return jax.tree_util.tree_unflatten(
            pickle.loads(meta["treedef_bytes"]), leaves)

    def restore_state_dict(self, template: Any,
                           step: Optional[int] = None) -> Any:
        """Restore into the structure of ``template`` (for states whose
        treedef carries unpicklable statics like optax transforms): array
        leaves are taken positionally from the checkpoint."""
        saved_leaves, _ = self._load(step)
        t_leaves, t_def = jax.tree_util.tree_flatten(template)
        if len(saved_leaves) != len(t_leaves):
            raise ValueError(
                f"checkpoint has {len(saved_leaves)} leaves, template has "
                f"{len(t_leaves)}")
        return jax.tree_util.tree_unflatten(t_def, saved_leaves)

    def metrics(self, step: int) -> Dict[str, float]:
        with open(os.path.join(self._step_dir(step), "structure.pkl"),
                  "rb") as f:
            return pickle.load(f)["metrics"]
