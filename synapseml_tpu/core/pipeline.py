"""Pipeline algebra: Estimator.fit(ds) -> Model; Transformer.transform(ds).

Re-designs Spark ML's Estimator/Transformer/Pipeline plus SynapseML's
``ComplexParamsWritable/Readable`` persistence (reference:
org/apache/spark/ml/ComplexParamsSerializer.scala:1-183) for the columnar
:class:`~synapseml_tpu.core.dataset.Dataset`.  Persistence layout:

    <path>/metadata.json      {class, uid, timestamp, simple params}
    <path>/complex/<name>.*   side-car per complex param (npz / pickle /
                              nested stage directory)

Every stage self-registers in a class registry keyed by qualified name so
generic ``load`` can reconstruct it — the analogue of SynapseML's
``JarLoadingUtils`` reflection over ``Wrappable`` stages.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .dataset import Dataset
from .params import (ComplexParam, DatasetParam, EstimatorParam, Param, Params,
                     PyObjectParam, TransformerParam, UDFParam)
from .logging import log_verb

_STAGE_REGISTRY: Dict[str, type] = {}


def _qualname(cls: type) -> str:
    return f"{cls.__module__}.{cls.__qualname__}"


def register_stage(cls: type) -> type:
    _STAGE_REGISTRY[_qualname(cls)] = cls
    return cls


def lookup_stage(name: str) -> type:
    if name in _STAGE_REGISTRY:
        return _STAGE_REGISTRY[name]
    # lazy import: module path is encoded in the qualified name
    module, _, _ = name.rpartition(".")
    import importlib
    importlib.import_module(module)
    if name not in _STAGE_REGISTRY:
        raise KeyError(f"stage class {name} not registered")
    return _STAGE_REGISTRY[name]


class PipelineStage(Params):
    """Common base: params + save/load."""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        register_stage(cls)

    # -- persistence -------------------------------------------------------
    def save(self, path: str, overwrite: bool = True) -> None:
        if os.path.exists(path) and not overwrite:
            raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        meta: Dict[str, Any] = {
            "class": _qualname(type(self)),
            "uid": self.uid,
            "timestamp": int(time.time() * 1000),
            "paramMap": {},
            "complexParams": [],
        }
        complex_dir = os.path.join(path, "complex")
        for name, value in self._paramMap.items():
            p = self.get_param(name)
            if value is None:
                meta["paramMap"][name] = None
            elif p.is_complex:
                os.makedirs(complex_dir, exist_ok=True)
                self._save_complex(complex_dir, p, value)
                meta["complexParams"].append(name)
            else:
                meta["paramMap"][name] = p.json_value(value)
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=1, default=_json_default)
        self._save_extra(path)

    def _save_extra(self, path: str) -> None:
        """Hook for stages with non-param state (e.g. fitted trees)."""

    def _load_extra(self, path: str) -> None:
        pass

    @staticmethod
    def _save_complex(complex_dir: str, p: Param, value: Any) -> None:
        base = os.path.join(complex_dir, p.name)
        if isinstance(p, (EstimatorParam, TransformerParam)) or isinstance(value, PipelineStage):
            value.save(base)
        elif isinstance(p, DatasetParam) or isinstance(value, Dataset):
            save_dataset(value, base)
        elif isinstance(value, np.ndarray) and value.dtype != object:
            np.save(base + ".npy", value)
        else:
            with open(base + ".pkl", "wb") as f:
                pickle.dump(value, f)

    @staticmethod
    def _load_complex(complex_dir: str, name: str) -> Any:
        base = os.path.join(complex_dir, name)
        if os.path.isdir(base):
            if os.path.exists(os.path.join(base, "metadata.json")):
                return load_stage(base)
            return load_dataset(base)
        if os.path.exists(base + ".npy"):
            return np.load(base + ".npy", allow_pickle=False)
        with open(base + ".pkl", "rb") as f:
            return pickle.load(f)

    @classmethod
    def load(cls, path: str) -> "PipelineStage":
        stage = load_stage(path)
        if cls is not PipelineStage and not isinstance(stage, cls):
            raise TypeError(f"{path} holds {type(stage).__name__}, not {cls.__name__}")
        return stage


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


def load_stage(path: str) -> PipelineStage:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    cls = lookup_stage(meta["class"])
    stage: PipelineStage = cls.__new__(cls)
    Params.__init__(stage)
    stage.uid = meta["uid"]
    for name, value in meta["paramMap"].items():
        if value is None:
            stage._paramMap[name] = None
        else:
            p = stage.get_param(name)
            stage._paramMap[name] = p.validate(p.from_json(value))
    complex_dir = os.path.join(path, "complex")
    for name in meta.get("complexParams", []):
        stage._paramMap[name] = stage._load_complex(complex_dir, name)
    stage._load_extra(path)
    return stage


def save_dataset(ds: Dataset, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    obj_cols = {k: v for k, v in ds._cols.items() if v.dtype == object}
    num_cols = {k: v for k, v in ds._cols.items() if v.dtype != object}
    np.savez(os.path.join(path, "columns.npz"), **num_cols)
    with open(os.path.join(path, "object_columns.pkl"), "wb") as f:
        pickle.dump(obj_cols, f)
    with open(os.path.join(path, "dsmeta.json"), "w") as f:
        json.dump({"num_partitions": ds.num_partitions,
                   "order": ds.columns}, f)


def load_dataset(path: str) -> Dataset:
    with open(os.path.join(path, "dsmeta.json")) as f:
        meta = json.load(f)
    cols: Dict[str, Any] = {}
    with np.load(os.path.join(path, "columns.npz")) as z:
        for k in z.files:
            cols[k] = z[k]
    with open(os.path.join(path, "object_columns.pkl"), "rb") as f:
        cols.update(pickle.load(f))
    ordered = {k: cols[k] for k in meta["order"]}
    return Dataset(ordered, meta["num_partitions"])


# --------------------------------------------------------------------------


class Transformer(PipelineStage):
    """ds -> ds map. Subclasses implement ``_transform``."""

    def transform(self, ds: Dataset) -> Dataset:
        with log_verb(self, "transform", n_rows=ds.num_rows):
            return self._transform(ds)

    def _transform(self, ds: Dataset) -> Dataset:
        raise NotImplementedError

    def __call__(self, ds: Dataset) -> Dataset:
        return self.transform(ds)


class Estimator(PipelineStage):
    """ds -> Model. Subclasses implement ``_fit``."""

    def fit(self, ds: Dataset) -> "Model":
        with log_verb(self, "fit", n_rows=ds.num_rows):
            model = self._fit(ds)
        model._parent_uid = self.uid
        return model

    def _fit(self, ds: Dataset) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer produced by an Estimator."""

    _parent_uid: Optional[str] = None


class Evaluator(Params):
    """ds -> float metric."""

    def evaluate(self, ds: Dataset) -> float:
        raise NotImplementedError

    def is_larger_better(self) -> bool:
        return True


# --------------------------------------------------------------------------


class Pipeline(Estimator):
    """Sequential stage composition (Spark ML Pipeline semantics)."""

    stages = PyObjectParam(doc="ordered list of pipeline stages")

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set("stages", list(stages))

    def _fit(self, ds: Dataset) -> "PipelineModel":
        fitted: List[Transformer] = []
        cur = ds
        stages = self.get_or_default("stages") or []
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                if i < len(stages) - 1:
                    cur = model.transform(cur)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                if i < len(stages) - 1:
                    cur = stage.transform(cur)
            else:
                raise TypeError(f"stage {stage!r} is neither Estimator nor Transformer")
        return PipelineModel(fitted)


class PipelineModel(Model):
    stages = PyObjectParam(doc="ordered list of fitted transformers")

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set("stages", list(stages))

    def _transform(self, ds: Dataset) -> Dataset:
        cur = ds
        for stage in self.get_or_default("stages") or []:
            cur = stage.transform(cur)
        return cur
