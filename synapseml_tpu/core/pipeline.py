"""Pipeline algebra: Estimator.fit(ds) -> Model; Transformer.transform(ds).

Re-designs Spark ML's Estimator/Transformer/Pipeline plus SynapseML's
``ComplexParamsWritable/Readable`` persistence (reference:
org/apache/spark/ml/ComplexParamsSerializer.scala:1-183) for the columnar
:class:`~synapseml_tpu.core.dataset.Dataset`.  Persistence layout:

    <path>/metadata.json      {class, uid, timestamp, simple params}
    <path>/complex/<name>.*   side-car per complex param (npz / pickle /
                              nested stage directory)

Every stage self-registers in a class registry keyed by qualified name so
generic ``load`` can reconstruct it — the analogue of SynapseML's
``JarLoadingUtils`` reflection over ``Wrappable`` stages.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .dataset import Dataset
from .params import (ComplexParam, DatasetParam, EstimatorParam, Param, Params,
                     PyObjectParam, StringParam, TransformerParam, UDFParam)
from .logging import log_verb
from ..resilience.rowguard import (HANDLE_INVALID_MODES, guard_context,
                                   guarded_fit, guarded_transform)

_STAGE_REGISTRY: Dict[str, type] = {}


def _qualname(cls: type) -> str:
    return f"{cls.__module__}.{cls.__qualname__}"


def register_stage(cls: type) -> type:
    _STAGE_REGISTRY[_qualname(cls)] = cls
    return cls


def lookup_stage(name: str) -> type:
    if name in _STAGE_REGISTRY:
        return _STAGE_REGISTRY[name]
    # lazy import: module path is encoded in the qualified name
    module, _, _ = name.rpartition(".")
    import importlib
    importlib.import_module(module)
    if name not in _STAGE_REGISTRY:
        raise KeyError(f"stage class {name} not registered")
    return _STAGE_REGISTRY[name]


class PipelineStage(Params):
    """Common base: params + save/load + row-level fault policy.

    Every stage carries the Spark ML ``handleInvalid`` contract, enforced
    at ``fit``/``transform`` entry by
    :mod:`synapseml_tpu.resilience.rowguard`: ``"error"`` (default) is a
    strict pass-through, ``"skip"`` drops rows that fail the stage
    (NaN/Inf screens on declared input columns + poison-batch bisection
    on stage exceptions), ``"quarantine"`` additionally dead-letters them
    with source-row provenance for later :meth:`Quarantine.replay`.
    """

    handleInvalid = StringParam(
        doc="row-level fault mode: 'error' raises on the first bad row "
            "(Spark default), 'skip' drops bad rows, 'quarantine' routes "
            "them to the dead-letter store",
        default="error", allowed=HANDLE_INVALID_MODES)
    quarantineDir = StringParam(
        doc="dead-letter directory for handleInvalid='quarantine' "
            "(default: $SML_QUARANTINE_DIR, else ./sml_quarantine)")

    #: params whose values name input columns the row guard
    #: contract-checks (existence) and screens (NaN/Inf/None) — extend
    #: per stage family when the input lives under another name
    _guard_input_params = ("inputCol", "inputCols")
    _guard_fit_params = ("labelCol",)
    #: stages whose JOB is consuming NaN (imputers, NaN-native trainers)
    #: opt out of the NaN/Inf screen; bisection still applies
    _guard_screen_nan = True
    #: containers (Pipeline) that propagate the policy to their children
    #: instead of being guarded themselves
    _guard_exempt = False

    def guard_input_columns(self, for_fit: bool = False) -> List[str]:
        """Columns the row guard requires + screens for this invocation,
        resolved from the declared ``_guard_input_params`` (plus
        ``_guard_fit_params`` for ``fit``)."""
        names = self._guard_input_params
        if for_fit:
            names = tuple(names) + tuple(self._guard_fit_params)
        po = self.param_objs()
        cols: List[str] = []
        for name in names:
            if name not in po:
                continue
            v = self.get_or_default(name)
            if isinstance(v, str) and v:
                cols.append(v)
            elif isinstance(v, (list, tuple)):
                cols.extend(c for c in v if isinstance(c, str) and c)
        return cols

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        register_stage(cls)

    # -- persistence -------------------------------------------------------
    def save(self, path: str, overwrite: bool = True) -> None:
        if os.path.exists(path) and not overwrite:
            raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        meta: Dict[str, Any] = {
            "class": _qualname(type(self)),
            "uid": self.uid,
            "timestamp": int(time.time() * 1000),
            "paramMap": {},
            "complexParams": [],
        }
        complex_dir = os.path.join(path, "complex")
        for name, value in self._paramMap.items():
            p = self.get_param(name)
            if value is None:
                meta["paramMap"][name] = None
            elif p.is_complex:
                os.makedirs(complex_dir, exist_ok=True)
                self._save_complex(complex_dir, p, value)
                meta["complexParams"].append(name)
            else:
                meta["paramMap"][name] = p.json_value(value)
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=1, default=_json_default)
        self._save_extra(path)

    def _save_extra(self, path: str) -> None:
        """Hook for stages with non-param state (e.g. fitted trees)."""

    def _load_extra(self, path: str) -> None:
        pass

    @staticmethod
    def _save_complex(complex_dir: str, p: Param, value: Any) -> None:
        base = os.path.join(complex_dir, p.name)
        if isinstance(p, (EstimatorParam, TransformerParam)) or isinstance(value, PipelineStage):
            value.save(base)
        elif isinstance(p, DatasetParam) or isinstance(value, Dataset):
            save_dataset(value, base)
        elif isinstance(value, np.ndarray) and value.dtype != object:
            np.save(base + ".npy", value)
        else:
            with open(base + ".pkl", "wb") as f:
                pickle.dump(value, f)

    @staticmethod
    def _load_complex(complex_dir: str, name: str) -> Any:
        base = os.path.join(complex_dir, name)
        if os.path.isdir(base):
            if os.path.exists(os.path.join(base, "metadata.json")):
                return load_stage(base)
            return load_dataset(base)
        if os.path.exists(base + ".npy"):
            return np.load(base + ".npy", allow_pickle=False)
        with open(base + ".pkl", "rb") as f:
            return pickle.load(f)

    @classmethod
    def load(cls, path: str) -> "PipelineStage":
        stage = load_stage(path)
        if cls is not PipelineStage and not isinstance(stage, cls):
            raise TypeError(f"{path} holds {type(stage).__name__}, not {cls.__name__}")
        return stage


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


def load_stage(path: str) -> PipelineStage:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    cls = lookup_stage(meta["class"])
    stage: PipelineStage = cls.__new__(cls)
    Params.__init__(stage)
    stage.uid = meta["uid"]
    for name, value in meta["paramMap"].items():
        if value is None:
            stage._paramMap[name] = None
        else:
            p = stage.get_param(name)
            stage._paramMap[name] = p.validate(p.from_json(value))
    complex_dir = os.path.join(path, "complex")
    for name in meta.get("complexParams", []):
        stage._paramMap[name] = stage._load_complex(complex_dir, name)
    stage._load_extra(path)
    return stage


def save_dataset(ds: Dataset, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    obj_cols = {k: v for k, v in ds._cols.items() if v.dtype == object}
    num_cols = {k: v for k, v in ds._cols.items() if v.dtype != object}
    np.savez(os.path.join(path, "columns.npz"), **num_cols)
    with open(os.path.join(path, "object_columns.pkl"), "wb") as f:
        pickle.dump(obj_cols, f)
    with open(os.path.join(path, "dsmeta.json"), "w") as f:
        json.dump({"num_partitions": ds.num_partitions,
                   "order": ds.columns}, f)


def load_dataset(path: str) -> Dataset:
    with open(os.path.join(path, "dsmeta.json")) as f:
        meta = json.load(f)
    cols: Dict[str, Any] = {}
    with np.load(os.path.join(path, "columns.npz")) as z:
        for k in z.files:
            cols[k] = z[k]
    with open(os.path.join(path, "object_columns.pkl"), "rb") as f:
        cols.update(pickle.load(f))
    ordered = {k: cols[k] for k in meta["order"]}
    return Dataset(ordered, meta["num_partitions"])


# --------------------------------------------------------------------------


class Transformer(PipelineStage):
    """ds -> ds map. Subclasses implement ``_transform``; the public
    ``transform`` routes through the row guard (a pass-through in the
    default ``handleInvalid='error'`` mode)."""

    def transform(self, ds: Dataset) -> Dataset:
        with log_verb(self, "transform", n_rows=ds.num_rows):
            return guarded_transform(self, ds)

    def _transform(self, ds: Dataset) -> Dataset:
        raise NotImplementedError

    def __call__(self, ds: Dataset) -> Dataset:
        return self.transform(ds)


class Estimator(PipelineStage):
    """ds -> Model. Subclasses implement ``_fit``; the public ``fit``
    routes through the row guard (a pass-through in the default
    ``handleInvalid='error'`` mode)."""

    def fit(self, ds: Dataset) -> "Model":
        with log_verb(self, "fit", n_rows=ds.num_rows):
            model = guarded_fit(self, ds)
        model._parent_uid = self.uid
        return model

    def _fit(self, ds: Dataset) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer produced by an Estimator."""

    _parent_uid: Optional[str] = None


class Evaluator(Params):
    """ds -> float metric."""

    def evaluate(self, ds: Dataset) -> float:
        raise NotImplementedError

    def is_larger_better(self) -> bool:
        return True


# --------------------------------------------------------------------------


class Pipeline(Estimator):
    """Sequential stage composition (Spark ML Pipeline semantics).

    A ``handleInvalid``/``quarantineDir`` set on the Pipeline propagates
    to every stage invocation (stages with their own explicit setting
    win), and source-row provenance is attached at entry so a row
    quarantined N stages deep still names the PIPELINE-input row that
    produced it."""

    stages = PyObjectParam(doc="ordered list of pipeline stages")
    #: the pipeline is not itself bisected — it propagates the policy to
    #: its children, which are
    _guard_exempt = True

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set("stages", list(stages))

    def _guard_ctx(self):
        mode = self._paramMap.get("handleInvalid")
        qdir = self._paramMap.get("quarantineDir")
        return guard_context(mode, qdir) if (mode or qdir) else None

    def _fit(self, ds: Dataset) -> "PipelineModel":
        ctx = self._guard_ctx()
        if ctx is None:
            return self._fit_stages(ds)
        with ctx:
            return self._fit_stages(ds.with_source_index())

    def _fit_stages(self, ds: Dataset) -> "PipelineModel":
        fitted: List[Transformer] = []
        cur = ds
        stages = self.get_or_default("stages") or []
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                if i < len(stages) - 1:
                    cur = model.transform(cur)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                if i < len(stages) - 1:
                    cur = stage.transform(cur)
            else:
                raise TypeError(f"stage {stage!r} is neither Estimator nor Transformer")
        model = PipelineModel(fitted)
        for name in ("handleInvalid", "quarantineDir"):
            if self.is_set(name):         # policy rides along to serving
                model.set(name, self.get(name))
        return model


class PipelineModel(Model):
    stages = PyObjectParam(doc="ordered list of fitted transformers")
    _guard_exempt = True

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set("stages", list(stages))

    def _transform(self, ds: Dataset) -> Dataset:
        mode = self._paramMap.get("handleInvalid")
        qdir = self._paramMap.get("quarantineDir")
        if not (mode or qdir):
            cur = ds
            for stage in self.get_or_default("stages") or []:
                cur = stage.transform(cur)
            return cur
        with guard_context(mode, qdir):
            cur = ds.with_source_index()
            for stage in self.get_or_default("stages") or []:
                cur = stage.transform(cur)
            return cur
