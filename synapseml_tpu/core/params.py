"""Typed parameter system for pipeline stages.

Re-designs the reference's Spark ML ``Params`` + SynapseML custom param types
(reference: core/src/main/scala/com/microsoft/azure/synapse/ml/param/*.scala,
core/serialize/ComplexParam.scala) as Python descriptors with full
introspection.  Every pipeline stage declares class-level :class:`Param`
objects; instances carry a ``paramMap`` of explicitly-set values over a
``defaultParamMap``.  Introspection (``stage.params``) powers generic
serialization and the fuzzing test harness, the way Spark param metadata
powers SynapseML's codegen (reference: core/.../codegen/Wrappable.scala).
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence


class Param:
    """A typed parameter declared on a stage class.

    Acts as a Python descriptor: ``stage.myParam`` reads the effective value
    (set value, else default); assignment sets it with validation.
    """

    #: set by subclasses that cannot be JSON-serialized inline (arrays,
    #: models, datasets, callables) — analogue of reference ComplexParam.
    is_complex = False

    def __init__(self, name: str = None, doc: str = "", default: Any = None,
                 validator: Optional[Callable[[Any], bool]] = None):
        self.name = name
        self.doc = doc
        self.default = default
        self.validator = validator

    def __set_name__(self, owner, name):
        if self.name is None:
            self.name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj.get_or_default(self.name)

    def __set__(self, obj, value):
        obj.set(self.name, value)

    # -- type plumbing -----------------------------------------------------
    def validate(self, value) -> Any:
        """Coerce + validate; raise TypeError/ValueError on bad input."""
        value = self._coerce(value)
        if self.validator is not None and value is not None:
            if not self.validator(value):
                raise ValueError(
                    f"Param {self.name}: value {value!r} failed validation")
        return value

    def _coerce(self, value):
        return value

    def json_value(self, value):
        """Representation for metadata.json (simple params only)."""
        return value

    def from_json(self, value):
        return value

    def __repr__(self):
        return f"{type(self).__name__}({self.name!r}, default={self.default!r})"


class IntParam(Param):
    def _coerce(self, value):
        if value is None:
            return None
        if isinstance(value, bool):
            raise TypeError(f"Param {self.name}: expected int, got bool")
        if isinstance(value, float) and value.is_integer():
            return int(value)
        if not isinstance(value, int):
            raise TypeError(f"Param {self.name}: expected int, got {type(value).__name__}")
        return value


class FloatParam(Param):
    def _coerce(self, value):
        if value is None:
            return None
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise TypeError(f"Param {self.name}: expected float, got {type(value).__name__}")
        return float(value)


class BoolParam(Param):
    def _coerce(self, value):
        if value is None:
            return None
        if not isinstance(value, bool):
            raise TypeError(f"Param {self.name}: expected bool, got {type(value).__name__}")
        return value


class StringParam(Param):
    def __init__(self, name=None, doc="", default=None, validator=None,
                 allowed: Optional[Sequence[str]] = None):
        super().__init__(name, doc, default, validator)
        self.allowed = tuple(allowed) if allowed else None

    def _coerce(self, value):
        if value is None:
            return None
        if not isinstance(value, str):
            raise TypeError(f"Param {self.name}: expected str, got {type(value).__name__}")
        if self.allowed and value not in self.allowed:
            raise ValueError(
                f"Param {self.name}: {value!r} not in allowed values {self.allowed}")
        return value


class ListParam(Param):
    """A list of simple values (ints/floats/strings)."""

    def _coerce(self, value):
        if value is None:
            return None
        if isinstance(value, (list, tuple)):
            return list(value)
        raise TypeError(f"Param {self.name}: expected list, got {type(value).__name__}")


class DictParam(Param):
    def _coerce(self, value):
        if value is None:
            return None
        if not isinstance(value, dict):
            raise TypeError(f"Param {self.name}: expected dict, got {type(value).__name__}")
        return dict(value)


# --------------------------------------------------------------------------
# Complex params — values that need side-car files to serialize
# (reference: core/serialize/ComplexParam.scala and descendants:
#  UDFParam, DataFrameParam, EstimatorParam, TransformerParam, ArrayParam)
# --------------------------------------------------------------------------

class ComplexParam(Param):
    is_complex = True

    def json_value(self, value):  # stored as a pointer to the side-car
        raise RuntimeError("complex params are not inline-JSON serializable")


class ArrayParam(ComplexParam):
    """numpy / jax array valued param (e.g. initial scores, sample weights)."""

    def _coerce(self, value):
        if value is None:
            return None
        import numpy as np
        return np.asarray(value)


class UDFParam(ComplexParam):
    """Callable-valued param (reference: param/UDFParam.scala)."""

    def _coerce(self, value):
        if value is None:
            return None
        if not callable(value):
            raise TypeError(f"Param {self.name}: expected callable")
        return value


class EstimatorParam(ComplexParam):
    """Pipeline-stage-valued param (reference: param/EstimatorParam.scala)."""


class TransformerParam(ComplexParam):
    """Transformer-valued param (reference: param/PipelineStageParam)."""


class DatasetParam(ComplexParam):
    """Dataset-valued param (reference: param/DataFrameParam.scala)."""


class PyObjectParam(ComplexParam):
    """Arbitrary picklable object (pytrees of model weights etc.)."""


# --------------------------------------------------------------------------
# Params base
# --------------------------------------------------------------------------

def _next_uid(cls_name: str) -> str:
    import uuid
    return f"{cls_name}_{uuid.uuid4().hex[:12]}"


class Params:
    """Base for anything with params (stages, evaluators).

    Mirrors Spark ML ``Params`` semantics: an explicit ``paramMap`` layered
    over ``defaultParamMap``; ``copy`` produces an independent clone.
    """

    def __init__(self, **kwargs):
        self.uid = _next_uid(type(self).__name__)
        self._paramMap: Dict[str, Any] = {}
        self.set_params(**kwargs)

    # -- declaration introspection ----------------------------------------
    @classmethod
    def param_objs(cls) -> Dict[str, Param]:
        # cached per class (params are class declarations, so the walk is
        # invariant); cls.__dict__ lookup keeps subclasses from aliasing
        # their parent's cache.  Callers treat the dict as read-only —
        # this sits on the per-row hot path of the pipeline guard.
        cached = cls.__dict__.get("_param_objs_cache")
        if cached is not None:
            return cached
        out: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for key, val in vars(klass).items():
                if isinstance(val, Param):
                    out[val.name] = val
        cls._param_objs_cache = out
        return out

    @property
    def params(self) -> List[Param]:
        return list(self.param_objs().values())

    def get_param(self, name: str) -> Param:
        try:
            return self.param_objs()[name]
        except KeyError:
            raise AttributeError(
                f"{type(self).__name__} has no param {name!r}") from None

    def has_param(self, name: str) -> bool:
        return name in self.param_objs()

    # -- get/set -----------------------------------------------------------
    def set(self, name: str, value: Any) -> "Params":
        p = self.get_param(name)
        self._paramMap[name] = p.validate(value)
        return self

    def set_params(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            self.set(k, v)
        return self

    def get(self, name: str) -> Any:
        self.get_param(name)
        return self._paramMap.get(name)

    def is_set(self, name: str) -> bool:
        return name in self._paramMap

    def is_defined(self, name: str) -> bool:
        return self.is_set(name) or self.get_param(name).default is not None

    def clear(self, name: str) -> "Params":
        self._paramMap.pop(name, None)
        return self

    # -- cloning -----------------------------------------------------------
    def copy(self, extra: Optional[Dict[str, Any]] = None) -> "Params":
        new = _copy.copy(self)
        new._paramMap = dict(self._paramMap)
        if hasattr(self, "_defaultOverrides"):
            new._defaultOverrides = dict(self._defaultOverrides)
        if extra:
            for k, v in extra.items():
                new.set(k, v)
        return new

    def _copy_values_from(self, other: "Params") -> "Params":
        """Copy explicitly-set values of shared params from ``other``
        (estimator -> model param transfer)."""
        for name, value in other._paramMap.items():
            if self.has_param(name):
                self.set(name, value)
        return self

    def explain_params(self) -> str:
        lines = []
        for p in self.params:
            cur = self._paramMap.get(p.name, "undefined")
            lines.append(f"{p.name}: {p.doc} (default: {p.default!r}, current: {cur!r})")
        return "\n".join(lines)

    def _set_default(self, **kwargs) -> "Params":
        """Override declared defaults for this instance (Spark setDefault)."""
        for k, v in kwargs.items():
            p = self.get_param(k)
            # store instance-level default by shadowing the class param map
            if not hasattr(self, "_defaultOverrides"):
                self._defaultOverrides: Dict[str, Any] = {}
            self._defaultOverrides[k] = p.validate(v)
        return self

    def get_or_default(self, name: str) -> Any:
        p = self.get_param(name)
        if name in self._paramMap:
            return self._paramMap[name]
        ov = getattr(self, "_defaultOverrides", None)
        if ov and name in ov:
            return ov[name]
        return p.default

    def __repr__(self):
        set_params = ", ".join(f"{k}={v!r}" for k, v in sorted(self._paramMap.items())
                               if not isinstance(v, (bytes,)))
        return f"{type(self).__name__}(uid={self.uid}, {set_params})"


class HasInputCol(Params):
    inputCol = StringParam(doc="name of the input column")


class HasInputCols(Params):
    inputCols = ListParam(doc="names of the input columns")


class HasOutputCol(Params):
    outputCol = StringParam(doc="name of the output column")


class HasLabelCol(Params):
    labelCol = StringParam(doc="name of the label column", default="label")


class HasFeaturesCol(Params):
    featuresCol = StringParam(doc="name of the features column", default="features")


class HasPredictionCol(Params):
    predictionCol = StringParam(doc="name of the prediction column", default="prediction")


class HasWeightCol(Params):
    weightCol = StringParam(doc="name of the sample-weight column")


class HasProbabilityCol(Params):
    probabilityCol = StringParam(doc="name of the probability column", default="probability")


class HasRawPredictionCol(Params):
    rawPredictionCol = StringParam(doc="name of the raw-prediction (margin) column",
                                   default="rawPrediction")


class HasSeed(Params):
    seed = IntParam(doc="random seed", default=0)
