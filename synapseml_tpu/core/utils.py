"""Core runtime utilities.

Analogues of the reference's ``core/utils`` package:
- :class:`StopWatch` — core/utils/StopWatch.scala
- :func:`retry_with_timeout` — core/utils/FaultToleranceUtils.scala:9-31
  (retry backoffs 0/100/200/500 ms, per-attempt timeout)
- :func:`using` — core/env/StreamUtilities.using resource bracket
- :class:`SharedVariable` — per-process lazy singleton
  (io/http/SharedVariable.scala:17,36; used for per-executor shared state
  like LightGBM's SharedState, SharedState.scala:12-89)
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import re
import threading
import time
from typing import Any, Callable, Generic, Iterable, List, Optional, TypeVar

T = TypeVar("T")

DEFAULT_BACKOFFS_MS = (0, 100, 200, 500)


def retry_with_timeout(fn: Callable[[], T],
                       timeout_s: Optional[float] = None,
                       backoffs_ms: Iterable[int] = DEFAULT_BACKOFFS_MS) -> T:
    """Run ``fn`` with per-attempt timeout, retrying on failure with the
    reference's backoff schedule."""
    from ..resilience.faults import get_faults
    backoffs = list(backoffs_ms)
    last_exc: Optional[BaseException] = None
    for i, backoff in enumerate(backoffs):
        if backoff:
            # routed through the fault registry so the schedule is
            # recorded alongside every other backoff in the stack
            get_faults().sleep(backoff / 1e3, site="core.retry")
        try:
            if timeout_s is None:
                return fn()
            pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
            try:
                return pool.submit(fn).result(timeout=timeout_s)
            finally:
                # wait=False: a hung fn must not block the caller past the
                # timeout; the orphaned worker thread dies with the process
                pool.shutdown(wait=False)
        except BaseException as e:  # noqa: BLE001 - retry everything like the reference
            last_exc = e
    raise RuntimeError(f"retry_with_timeout exhausted {len(backoffs)} attempts") from last_exc


def retry(fn: Callable[[], T], times: List[int]) -> T:
    """HandlingUtils.retry analogue: try, sleep head of list, recurse on tail
    — i.e. len(times)+1 attempts, last error rethrown."""
    from ..resilience.faults import get_faults
    for backoff in times:
        try:
            return fn()
        except BaseException:
            get_faults().sleep(backoff / 1e3, site="core.retry")
    return fn()


@contextlib.contextmanager
def using(resource):
    """StreamUtilities.using: close() guaranteed."""
    try:
        yield resource
    finally:
        close = getattr(resource, "close", None)
        if close:
            close()


class StopWatch:
    """Accumulating stopwatch (reference: core/utils/StopWatch.scala)."""

    def __init__(self):
        self._elapsed_ns = 0
        self._start: Optional[int] = None

    def start(self) -> None:
        self._start = time.perf_counter_ns()

    def stop(self) -> None:
        if self._start is not None:
            self._elapsed_ns += time.perf_counter_ns() - self._start
            self._start = None

    def restart(self) -> None:
        self._elapsed_ns = 0
        self.start()

    @contextlib.contextmanager
    def measure(self):
        self.start()
        try:
            yield
        finally:
            self.stop()

    @property
    def elapsed_ns(self) -> int:
        running = (time.perf_counter_ns() - self._start) if self._start is not None else 0
        return self._elapsed_ns + running

    @property
    def elapsed_ms(self) -> float:
        return self.elapsed_ns / 1e6

    @property
    def elapsed_s(self) -> float:
        return self.elapsed_ns / 1e9


class SharedVariable(Generic[T]):
    """Lazily-constructed per-process singleton value with double-checked
    locking (reference: io/http/SharedVariable.scala,
    lightgbm SharedState main-worker election SharedState.scala:53-61)."""

    def __init__(self, ctor: Callable[[], T]):
        self._ctor = ctor
        self._lock = threading.Lock()
        self._value: Optional[T] = None
        self._built = False

    def get(self) -> T:
        if not self._built:
            with self._lock:
                if not self._built:
                    self._value = self._ctor()
                    self._built = True
        return self._value  # type: ignore[return-value]

    def reset(self) -> None:
        with self._lock:
            self._value = None
            self._built = False


class KahanSum:
    """Compensated summation (reference: vw/KahanSum.scala:68)."""

    __slots__ = ("_sum", "_c")

    def __init__(self, value: float = 0.0):
        self._sum = float(value)
        self._c = 0.0

    def add(self, x: float) -> "KahanSum":
        y = x - self._c
        t = self._sum + y
        self._c = (t - self._sum) - y
        self._sum = t
        return self

    @property
    def value(self) -> float:
        return self._sum

    def __iadd__(self, x: float) -> "KahanSum":
        return self.add(x)


def assert_models_equal(m1, m2, loose_params: Iterable[str] = ()) -> None:
    """Assert two pipeline stages have the same class and param values.

    TPU-build analogue of the reference's save/load equality check
    (core/utils/ModelEquality.scala:15-50): identical class, identical
    param-name sets, and equal values — except params named in
    ``loose_params`` (the reference hard-codes uid-bearing column names
    and randomly assigned ports), which only need matching presence.
    Numpy-array values compare with allclose.
    """
    import numpy as np

    if type(m1) is not type(m2):
        raise AssertionError(f"{type(m1)} != {type(m2)}")
    names1 = {p.name for p in m1.params}
    names2 = {p.name for p in m2.params}
    if names1 != names2:
        raise AssertionError(f"param sets differ: {names1 ^ names2}")
    loose = set(loose_params)
    for name in sorted(names1):
        if name in loose:
            continue
        v1, v2 = m1.get(name), m2.get(name)
        if isinstance(v1, np.ndarray) or isinstance(v2, np.ndarray):
            a1, a2 = np.asarray(v1), np.asarray(v2)
            if a1.shape != a2.shape:
                raise AssertionError(f"param {name}: shape {a1.shape} != {a2.shape}")
            if a1.dtype.kind in "fc":
                ok = np.allclose(a1, a2, equal_nan=True)
            else:
                ok = bool(np.array_equal(a1, a2))
            if not ok:
                raise AssertionError(f"param {name}: arrays differ")
        elif callable(v1) and callable(v2):
            continue  # UDFs compare by presence only, like ComplexParam
        elif (v1 is not None and v2 is not None
              and type(v1) is type(v2)
              and type(v1).__eq__ is object.__eq__):
            continue  # complex values with identity equality: presence only
        elif (isinstance(v1, float) and isinstance(v2, float)
              and np.isnan(v1) and np.isnan(v2)):
            continue  # NaN scalars match, like equal_nan for arrays
        elif v1 != v2:
            raise AssertionError(f"param {name}: {v1!r} != {v2!r}")


#: ``{column}`` interpolation slots shared by the prompt-templating stages
#: (services.openai.OpenAIPrompt and models.llm.LLMTransformer)
TEMPLATE_RE = re.compile(r"\{(\w+)\}")


def interpolate_template(template: str, lookup) -> str:
    """Replace ``{name}`` slots via ``lookup(name) -> Optional[str]``;
    slots whose lookup returns None (and literal braces) pass through."""
    def sub(m):
        v = lookup(m.group(1))
        return m.group(0) if v is None else str(v)
    return TEMPLATE_RE.sub(sub, template)
