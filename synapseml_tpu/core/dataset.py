"""Columnar Dataset — the TPU build's DataFrame.

The reference runs on Spark DataFrames whose partitions are the SPMD unit
(reference: LightGBMBase.scala:596-599 ``df.rdd.barrier().mapPartitions``).
Here a :class:`Dataset` is a host-resident columnar table (dict of numpy
arrays) carrying a ``num_partitions`` hint; partitions map deterministically
onto mesh devices via :mod:`synapseml_tpu.parallel.placement`.  Numeric
columns move to device as padded dense blocks; object columns (strings,
ragged lists) stay host-side for featurizers and service stages.
"""

from __future__ import annotations

import numpy as np
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Union


def _dedupe_names(names: Sequence[str]) -> List[str]:
    """Rename duplicate column names ``x`` → ``x_1``, ``x_2``… (dict-keyed
    columns would silently drop duplicates); shared by both CSV paths so
    strict and permissive modes produce identical schemas."""
    uniq: List[str] = []
    for n in names:
        if n in uniq:
            base, k = n, 1
            while f"{base}_{k}" in uniq or f"{base}_{k}" in names:
                k += 1
            n = f"{base}_{k}"
        uniq.append(n)
    return uniq


def _as_column(values, n_rows: Optional[int] = None) -> np.ndarray:
    if isinstance(values, np.ndarray):
        arr = values
    else:
        values = list(values)
        if values and isinstance(values[0], (list, tuple, np.ndarray, dict, bytes)):
            arr = np.empty(len(values), dtype=object)
            for i, v in enumerate(values):
                arr[i] = v
        else:
            arr = np.asarray(values)
            if arr.dtype.kind in ("U", "S"):
                arr = arr.astype(object)
    if n_rows is not None and len(arr) != n_rows:
        raise ValueError(f"column length {len(arr)} != {n_rows}")
    return arr


class Dataset:
    """Immutable columnar table with partition metadata.

    ``row_index`` is optional SOURCE-row provenance: once attached (via
    :meth:`with_source_index`, typically by the row guard at a pipeline
    boundary), every row operation (``filter``, ``_mask_rows``, ``sort``,
    ``union``, batching, …) carries it along, so a row skipped or
    quarantined three stages deep still points at the row of the ORIGINAL
    input that produced it.  Untracked datasets pay nothing.
    """

    def __init__(self, columns: Dict[str, Any], num_partitions: int = 1,
                 row_index: Optional[np.ndarray] = None):
        if not columns:
            raise ValueError("Dataset needs at least one column")
        n = None
        cols: Dict[str, np.ndarray] = {}
        for name, vals in columns.items():
            arr = _as_column(vals, n)
            if n is None:
                n = len(arr)
            cols[name] = arr
        self._cols = cols
        self._n = int(n)
        self.num_partitions = max(1, min(int(num_partitions), self._n or 1))
        if row_index is not None:
            row_index = np.asarray(row_index, dtype=np.int64)
            if len(row_index) != self._n:
                raise ValueError(
                    f"row_index length {len(row_index)} != {self._n} rows")
        self._row_index = row_index

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_dict(d: Dict[str, Any], num_partitions: int = 1) -> "Dataset":
        return Dataset(d, num_partitions)

    @staticmethod
    def from_rows(rows: Sequence[Dict[str, Any]], num_partitions: int = 1,
                  handle_invalid: str = "error",
                  quarantine: Any = None) -> "Dataset":
        """Build from a list of row dicts.

        ``handle_invalid="error"`` (default) keeps the strict behavior: a
        row missing a key raises.  ``"skip"`` drops ragged rows (non-dict
        rows and rows MISSING one of the schema's keys; extra keys are
        ignored, exactly as the strict path ignores them);
        ``"quarantine"`` additionally writes them — with their row
        numbers — to the dead-letter store (``quarantine``: a
        Quarantine, a directory, or None for the default dir)."""
        if not rows:
            raise ValueError("no rows")
        if handle_invalid == "error":
            keys = list(rows[0].keys())
            return Dataset({k: [r[k] for r in rows] for k in keys},
                           num_partitions)
        # permissive: the schema comes from the FIRST DICT row — a
        # non-dict row 0 is exactly the input this mode must tolerate
        first = next((r for r in rows if isinstance(r, dict)), None)
        if first is None:
            raise ValueError(f"no dict rows among {len(rows)} inputs")
        keys = list(first.keys())
        keyset = set(keys)
        good: List[Dict[str, Any]] = []
        good_idx: List[int] = []
        bad: List[Tuple[int, Any, str]] = []
        for i, r in enumerate(rows):
            if not isinstance(r, dict):
                bad.append((i, r, f"row {i} is {type(r).__name__}, "
                            "not a dict"))
            elif not keyset.issubset(r.keys()):
                # extra keys are fine (the strict path ignores them too);
                # only MISSING schema keys make a row ragged
                bad.append((i, r, f"ragged row {i}: missing keys "
                            f"{sorted(map(str, keyset - set(r.keys())))}"))
            else:
                good.append(r)
                good_idx.append(i)
        Dataset._report_ingest_invalid(
            "Dataset.from_rows", handle_invalid, quarantine,
            [(i, repr(r), msg) for i, r, msg in bad])
        if not good:
            raise ValueError(
                f"no valid rows: all {len(rows)} rows were ragged "
                f"(first: {bad[0][2]})")
        return Dataset({k: [r[k] for r in good] for k in keys},
                       num_partitions,
                       row_index=np.asarray(good_idx, dtype=np.int64))

    @staticmethod
    def from_pandas(df, num_partitions: int = 1) -> "Dataset":
        return Dataset({c: df[c].to_numpy() for c in df.columns}, num_partitions)

    @staticmethod
    def _report_ingest_invalid(source: str, handle_invalid: str,
                               quarantine: Any,
                               bad: Sequence[Tuple[int, str, str]]) -> None:
        """Route ingest-time invalid rows/lines (``(index, raw, reason)``)
        through the skip/quarantine policy + telemetry."""
        if handle_invalid not in ("skip", "quarantine"):
            raise ValueError(
                f"handle_invalid must be 'error', 'skip' or 'quarantine', "
                f"got {handle_invalid!r}")
        if not bad:
            return
        from ..resilience.rowguard import ErrorRecord, Quarantine
        from ..telemetry import get_registry
        from .logging import logger
        records = [ErrorRecord(stage_uid=source, stage_class=source,
                               row_index=int(i), error_class="ParseError",
                               error_message=msg, verb="ingest")
                   for i, _, msg in bad]
        get_registry().counter(
            "rowguard_rows_total", "rows screened out by the guard",
            ("stage", "outcome")).inc(len(bad), stage=source,
                                      outcome=handle_invalid)
        if handle_invalid == "quarantine":
            store = (quarantine if isinstance(quarantine, Quarantine)
                     else Quarantine(quarantine))
            rows = Dataset(
                {"raw": [raw for _, raw, _ in bad]},
                row_index=np.asarray([i for i, _, _ in bad],
                                     dtype=np.int64))
            store.add(source, rows, records, stage_class=source)
        logger.warning("%s: %s %d invalid row(s) (first: %s)",
                       source, handle_invalid, len(bad), bad[0][2])

    @staticmethod
    def from_csv(path: str, delim: str = ",",
                 num_partitions: int = 1, handle_invalid: str = "error",
                 quarantine: Any = None) -> "Dataset":
        """Numeric CSV via the native C++ parser (multithreaded mmap parse;
        see synapseml_tpu/native/loader.cpp), numpy fallback.

        ``handle_invalid="skip"``/``"quarantine"`` switches to a
        permissive line-validating parse: ragged lines (wrong field
        count) and unparseable fields are dropped or dead-lettered with
        their file line numbers instead of crashing the native parser,
        and columns that parse to all-NaN are reported (they usually mean
        a text column fed to a numeric reader)."""
        if handle_invalid != "error":
            return Dataset._from_csv_permissive(
                path, delim, num_partitions, handle_invalid, quarantine)
        from ..native import read_csv_matrix
        mat, names = read_csv_matrix(path, delim)
        return Dataset({n: mat[:, i].copy()
                        for i, n in enumerate(_dedupe_names(names))},
                       num_partitions)

    @staticmethod
    def _from_csv_permissive(path: str, delim: str, num_partitions: int,
                             handle_invalid: str,
                             quarantine: Any) -> "Dataset":
        from ..native import _read_header
        has_header, names = _read_header(path, delim)
        names = _dedupe_names(names)
        good: List[List[float]] = []
        good_idx: List[int] = []
        bad: List[Tuple[int, str, str]] = []
        ncols = len(names)
        with open(path, "r", errors="replace") as f:
            if has_header:
                f.readline()
            data_row = 0
            for lineno, line in enumerate(f, start=2 if has_header else 1):
                raw = line.rstrip("\r\n")
                if not raw.strip():
                    continue
                fields = raw.split(delim)
                if len(fields) != ncols:
                    bad.append((data_row, raw,
                                f"line {lineno}: {len(fields)} fields, "
                                f"expected {ncols}"))
                    data_row += 1
                    continue
                try:
                    # empty fields are missing values (genfromtxt parity)
                    vals = [float(x) if x.strip() else float("nan")
                            for x in fields]
                except ValueError as e:
                    bad.append((data_row, raw, f"line {lineno}: {e}"))
                    data_row += 1
                    continue
                good.append(vals)
                good_idx.append(data_row)
                data_row += 1
        Dataset._report_ingest_invalid("Dataset.from_csv", handle_invalid,
                                       quarantine, bad)
        if not good:
            raise ValueError(f"{path}: no parseable data lines "
                             f"({len(bad)} invalid)")
        mat = np.asarray(good, dtype=np.float32)
        all_nan = [names[j] for j in range(ncols)
                   if bool(np.all(np.isnan(mat[:, j])))]
        if all_nan:
            from ..telemetry import get_registry
            from .logging import logger
            for c in all_nan:
                get_registry().counter(
                    "dataset_all_nan_columns_total",
                    "columns that parsed to all-NaN on CSV ingest",
                    ("column",)).inc(1, column=c)
            logger.warning("%s: columns %s parsed to all-NaN — likely "
                           "non-numeric data in a numeric reader",
                           path, all_nan)
        return Dataset({n: mat[:, j].copy() for j, n in enumerate(names)},
                       num_partitions,
                       row_index=np.asarray(good_idx, dtype=np.int64))

    @staticmethod
    def from_colstore(path: str, columns: Optional[Sequence[str]] = None,
                      num_partitions: int = 1) -> "Dataset":
        """Binary SMLC column store (native fast path)."""
        from ..native import read_colstore
        mat = read_colstore(path)
        if columns is not None and len(columns) != mat.shape[1]:
            raise ValueError(f"column store {path} holds {mat.shape[1]} "
                             f"columns but {len(columns)} names were given")
        names = (list(columns) if columns
                 else [f"f{i}" for i in range(mat.shape[1])])
        return Dataset({n: mat[:, i].copy() for i, n in enumerate(names)},
                       num_partitions)

    def to_colstore(self, path: str, cols: Optional[Sequence[str]] = None) -> None:
        from ..native import write_colstore
        use = (list(cols) if cols is not None
               else [c for c in self.columns
                     if self._cols[c].dtype != object])
        if not use:
            raise ValueError("to_colstore: no numeric columns to write")
        write_colstore(path, self.to_numpy(use))

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame({k: list(v) if v.dtype == object else v
                             for k, v in self._cols.items()})

    # -- source-row provenance --------------------------------------------
    @property
    def source_index(self) -> np.ndarray:
        """Source-row index per row: the tracked provenance when attached,
        else each row's own position (identity)."""
        if self._row_index is not None:
            return self._row_index
        return np.arange(self._n, dtype=np.int64)

    @property
    def has_source_index(self) -> bool:
        return self._row_index is not None

    def with_source_index(self, index: Optional[Any] = None) -> "Dataset":
        """Attach source-row provenance (identity when ``index`` is None);
        a no-op when already tracked and no explicit index is given."""
        if index is None:
            if self._row_index is not None:
                return self
            index = np.arange(self._n, dtype=np.int64)
        return Dataset(self._cols, self.num_partitions, row_index=index)

    # -- basic introspection ----------------------------------------------
    @property
    def columns(self) -> List[str]:
        return list(self._cols.keys())

    @property
    def num_rows(self) -> int:
        return self._n

    def __len__(self) -> int:
        return self._n

    def __contains__(self, col: str) -> bool:
        return col in self._cols

    def __getitem__(self, col: str) -> np.ndarray:
        return self._cols[col]

    def column(self, col: str) -> np.ndarray:
        return self._cols[col]

    def schema(self) -> Dict[str, str]:
        return {k: str(v.dtype) for k, v in self._cols.items()}

    def dtypes(self) -> Dict[str, np.dtype]:
        return {k: v.dtype for k, v in self._cols.items()}

    # -- projections -------------------------------------------------------
    def select(self, *cols: str) -> "Dataset":
        missing = [c for c in cols if c not in self._cols]
        if missing:
            raise KeyError(f"columns not found: {missing}; have {self.columns}")
        return Dataset({c: self._cols[c] for c in cols}, self.num_partitions,
                       row_index=self._row_index)

    def drop(self, *cols: str) -> "Dataset":
        keep = {k: v for k, v in self._cols.items() if k not in cols}
        return Dataset(keep, self.num_partitions, row_index=self._row_index)

    def with_column(self, name: str, values) -> "Dataset":
        cols = dict(self._cols)
        cols[name] = _as_column(values, self._n)
        return Dataset(cols, self.num_partitions, row_index=self._row_index)

    def with_columns(self, new: Dict[str, Any]) -> "Dataset":
        cols = dict(self._cols)
        for name, values in new.items():
            cols[name] = _as_column(values, self._n)
        return Dataset(cols, self.num_partitions, row_index=self._row_index)

    def rename(self, old: str, new: str) -> "Dataset":
        cols = {}
        for k, v in self._cols.items():
            cols[new if k == old else k] = v
        return Dataset(cols, self.num_partitions, row_index=self._row_index)

    # -- row ops -----------------------------------------------------------
    def take(self, n: int) -> "Dataset":
        return self._mask_rows(slice(0, n))

    def head(self, n: int = 5) -> List[Dict[str, Any]]:
        return self.take(min(n, self._n)).collect()

    def first(self) -> Dict[str, Any]:
        return {k: v[0] for k, v in self._cols.items()}

    def collect(self) -> List[Dict[str, Any]]:
        keys = self.columns
        return [{k: self._cols[k][i] for k in keys} for i in range(self._n)]

    def _mask_rows(self, idx) -> "Dataset":
        ri = self._row_index[idx] if self._row_index is not None else None
        return Dataset({k: v[idx] for k, v in self._cols.items()},
                       self.num_partitions, row_index=ri)

    def filter(self, pred: Union[np.ndarray, Callable[[Dict[str, Any]], bool]]) -> "Dataset":
        if callable(pred):
            mask = np.fromiter((bool(pred(r)) for r in self.iter_rows()),
                               dtype=bool, count=self._n)
        else:
            mask = np.asarray(pred, dtype=bool)
        return self._mask_rows(mask)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        keys = self.columns
        for i in range(self._n):
            yield {k: self._cols[k][i] for k in keys}

    def sort(self, col: str, ascending: bool = True) -> "Dataset":
        order = np.argsort(self._cols[col], kind="stable")
        if not ascending:
            order = order[::-1]
        return self._mask_rows(order)

    def union(self, other: "Dataset") -> "Dataset":
        if set(self.columns) != set(other.columns):
            raise ValueError("union requires identical column sets")
        cols = {}
        for k in self.columns:
            a, b = self._cols[k], other._cols[k]
            if a.dtype == object or b.dtype == object:
                out = np.empty(len(a) + len(b), dtype=object)
                out[:len(a)] = a
                out[len(a):] = b
                cols[k] = out
            else:
                cols[k] = np.concatenate([a, b])
        # provenance survives only when BOTH sides track it (mixing a
        # tracked side with implicit positions would fabricate indices)
        ri = None
        if self._row_index is not None and other._row_index is not None:
            ri = np.concatenate([self._row_index, other._row_index])
        return Dataset(cols, self.num_partitions, row_index=ri)

    def sample(self, fraction: float, seed: int = 0) -> "Dataset":
        rng = np.random.default_rng(seed)
        mask = rng.random(self._n) < fraction
        return self._mask_rows(mask)

    def random_split(self, weights: Sequence[float], seed: int = 0) -> List["Dataset"]:
        rng = np.random.default_rng(seed)
        w = np.asarray(weights, dtype=np.float64)
        w = w / w.sum()
        assignment = rng.choice(len(w), size=self._n, p=w)
        return [self._mask_rows(assignment == i) for i in range(len(w))]

    def shuffle(self, seed: int = 0) -> "Dataset":
        rng = np.random.default_rng(seed)
        return self._mask_rows(rng.permutation(self._n))

    def group_by_agg(self, key: str, aggs: Dict[str, Tuple[str, str]]) -> "Dataset":
        """Tiny groupBy: aggs maps out_col -> (in_col, fn) with fn in
        {sum, mean, count, min, max}."""
        keys = self._cols[key]
        uniq, inv = np.unique(keys, return_inverse=True)
        out: Dict[str, Any] = {key: uniq}
        for out_col, (in_col, fn) in aggs.items():
            counts = np.bincount(inv, minlength=len(uniq))
            if fn == "count":
                out[out_col] = counts
                continue
            vals = self._cols[in_col].astype(np.float64)
            sums = np.bincount(inv, weights=vals, minlength=len(uniq))
            if fn == "sum":
                out[out_col] = sums
            elif fn == "mean":
                out[out_col] = sums / np.maximum(counts, 1)
            elif fn in ("min", "max"):
                red = np.full(len(uniq), np.inf if fn == "min" else -np.inf)
                op = np.minimum if fn == "min" else np.maximum
                op.at(red, inv, vals)
                out[out_col] = red
            else:
                raise ValueError(f"unknown agg {fn}")
        return Dataset(out, self.num_partitions)

    # -- partitioning (the Spark-partition analogue) -----------------------
    def repartition(self, n: int) -> "Dataset":
        return Dataset(self._cols, num_partitions=n,
                       row_index=self._row_index)

    def coalesce(self, n: int) -> "Dataset":
        return self.repartition(min(n, self.num_partitions))

    def partition_bounds(self) -> List[Tuple[int, int]]:
        """Deterministic contiguous row ranges, one per partition."""
        n, p = self._n, self.num_partitions
        base, rem = divmod(n, p)
        bounds, start = [], 0
        for i in range(p):
            size = base + (1 if i < rem else 0)
            bounds.append((start, start + size))
            start += size
        return bounds

    def partitions(self) -> List["Dataset"]:
        return [self._mask_rows(slice(a, b)) for a, b in self.partition_bounds()]

    def iter_batches(self, batch_size: int) -> Iterator["Dataset"]:
        for start in range(0, self._n, batch_size):
            yield self._mask_rows(slice(start, start + batch_size))

    # -- device materialization -------------------------------------------
    def to_numpy(self, cols: Sequence[str], dtype=np.float32) -> np.ndarray:
        """Stack numeric columns (or a single vector column) to a dense
        (rows, features) matrix — FastVectorAssembler analogue
        (reference: org/apache/spark/ml/feature/FastVectorAssembler.scala)."""
        if len(cols) == 1 and self._cols[cols[0]].dtype == object:
            col = self._cols[cols[0]]
            return np.stack([np.asarray(v, dtype=dtype) for v in col])
        return np.column_stack([self._cols[c].astype(dtype) for c in cols])

    def __repr__(self):
        return (f"Dataset({self._n} rows x {len(self._cols)} cols, "
                f"{self.num_partitions} partitions: {self.schema()})")


def find_unused_column_name(base: str, ds: Dataset) -> str:
    """reference: core/schema/DatasetExtensions.findUnusedColumnName."""
    if base not in ds:
        return base
    i = 1
    while f"{base}_{i}" in ds:
        i += 1
    return f"{base}_{i}"
