"""Profiling: JAX device traces + named host-side phase timing.

The reference instruments training with per-phase wall-clock measures
(reference: lightgbm/.../LightGBMPerformance.scala:11-111) and has no
device-level profiler; the TPU-native equivalent pairs host phase timing
(:class:`PhaseTimer`) with XLA's profiler (:func:`trace` writes a
TensorBoard-loadable trace of device ops, infeed, and collectives).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional

__all__ = ["PhaseTimer", "trace"]


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a JAX/XLA profiler trace into ``log_dir`` (view with
    TensorBoard or xprof).  Degrades to a no-op if the profiler is
    unavailable or a trace is already active — entry failures are caught,
    body exceptions are not."""
    ctx = None
    try:
        import jax
        ctx = jax.profiler.trace(log_dir, create_perfetto_link=False)
        ctx.__enter__()
    except Exception:  # pragma: no cover - profiler unavailable/active
        ctx = None
    try:
        yield
    finally:
        if ctx is not None:
            try:
                ctx.__exit__(None, None, None)
            except Exception:  # pragma: no cover
                pass


class PhaseTimer:
    """Accumulating named phase timer.

    >>> t = PhaseTimer()
    >>> with t.phase("binning"): ...
    >>> with t.phase("train"): ...
    >>> t.report()   # {"binning": 0.01, "train": 1.2}
    """

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._acc[name] = self._acc.get(name, 0.0) + (
                time.perf_counter() - t0)
            self._counts[name] = self._counts.get(name, 0) + 1

    def report(self) -> Dict[str, float]:
        return dict(self._acc)

    def counts(self) -> Dict[str, int]:
        return dict(self._counts)

    def reset(self) -> None:
        self._acc.clear()
        self._counts.clear()
