from .dataset import Dataset, find_unused_column_name
from .params import (ArrayParam, BoolParam, ComplexParam, DatasetParam,
                     DictParam, EstimatorParam, FloatParam, IntParam,
                     ListParam, Param, Params, PyObjectParam, StringParam,
                     TransformerParam, UDFParam)
from .pipeline import (Estimator, Evaluator, Model, Pipeline, PipelineModel,
                       PipelineStage, Transformer, load_dataset, load_stage,
                       save_dataset)
from .profiling import PhaseTimer, trace
from .utils import (KahanSum, SharedVariable, StopWatch,
                    assert_models_equal, retry, retry_with_timeout, using)
