"""Structured telemetry around every public verb.

Analogue of SynapseML's ``SynapseMLLogging`` which wraps every
constructor/fit/transform with structured JSON telemetry plus a PII scrubber
(reference: core/.../logging/SynapseMLLogging.scala:51-101,
logging/common/SASScrubber).  Emits one JSON record per verb via the stdlib
``logging`` module under the ``synapseml_tpu`` logger.
"""

from __future__ import annotations

import contextlib
import json
import logging
import re
import time
import traceback
from typing import Any, Dict

from .. import __version__ as _build_version

logger = logging.getLogger("synapseml_tpu")

_SAS_RE = re.compile(r"(sig=)[^&\s\"']+", re.IGNORECASE)
_KEY_RE = re.compile(r"(key=|token=|bearer\s+)[A-Za-z0-9+/=._-]{8,}", re.IGNORECASE)


def scrub(message: str) -> str:
    """Scrub SAS signatures / keys out of log text
    (reference: logging/common/SASScrubber.scala)."""
    message = _SAS_RE.sub(r"\1####", message)
    message = _KEY_RE.sub(r"\1####", message)
    return message


def _emit(payload: Dict[str, Any]) -> None:
    payload["buildVersion"] = _build_version
    try:
        logger.info(json.dumps(payload, default=str))
    except Exception:  # telemetry must never break the pipeline
        pass


@contextlib.contextmanager
def log_verb(stage, verb: str, **info):
    """Wraps fit/transform/predict with timing + error telemetry."""
    t0 = time.perf_counter()
    payload: Dict[str, Any] = {
        "className": type(stage).__name__,
        "uid": getattr(stage, "uid", None),
        "method": verb,
        **info,
    }
    try:
        yield
    except Exception as e:
        payload["error"] = scrub(f"{type(e).__name__}: {e}")
        payload["traceback"] = scrub(traceback.format_exc(limit=5))
        payload["elapsedMs"] = (time.perf_counter() - t0) * 1e3
        _emit(payload)
        raise
    payload["elapsedMs"] = (time.perf_counter() - t0) * 1e3
    _emit(payload)
