"""MurmurHash3 x86 32-bit — the hashing-trick primitive.

The reference's VW featurizer hashes feature names/values with murmur3,
with a pre-hashed-prefix optimization for column names
(reference: vw/src/main/scala/.../VowpalWabbitMurmurWithPrefix.scala:80,
VowpalWabbitFeaturizer.scala:150-165).  This implements the same algorithm
(public domain, Austin Appleby) in masked Python-int arithmetic — an order
of magnitude faster than numpy-scalar boxing in the per-token inner loop —
plus a column-level helper that hashes a whole token iterable at once.
"""

from __future__ import annotations

from typing import Iterable, List, Union

import numpy as np

_MASK = 0xFFFFFFFF
_C1 = 0xCC9E2D51
_C2 = 0x1B873593


def murmurhash3_32(data: Union[bytes, str], seed: int = 0) -> int:
    """murmur3_x86_32 of a byte/str payload; returns an unsigned 32-bit int."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    h = seed & _MASK
    n = len(data)
    nblocks = n >> 2
    for i in range(nblocks):
        k = int.from_bytes(data[4 * i:4 * i + 4], "little")
        k = (k * _C1) & _MASK
        k = ((k << 15) | (k >> 17)) & _MASK
        k = (k * _C2) & _MASK
        h ^= k
        h = ((h << 13) | (h >> 19)) & _MASK
        h = (h * 5 + 0xE6546B64) & _MASK
    tail = data[nblocks * 4:]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _MASK
        k = ((k << 15) | (k >> 17)) & _MASK
        k = (k * _C2) & _MASK
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return h


def murmurhash3_column(tokens: Iterable[str], seed: int = 0) -> np.ndarray:
    """Hash every token of a column in one call -> uint32 array.

    Uses the native batch hasher (synapseml_tpu/native/textproc.cpp) when
    the toolchain is available; Python murmur otherwise."""
    toks = tokens if isinstance(tokens, (list, tuple)) else list(tokens)
    from ..native import murmur3_batch
    hashed = murmur3_batch(toks, seed)
    if hashed is not None:
        return hashed
    return np.fromiter((murmurhash3_32(t, seed) for t in toks),
                       dtype=np.uint32)


class MurmurWithPrefix:
    """Hash ``prefix + value`` with the prefix pre-encoded once —
    the reference's trick for 'column-name + feature-value' hashes
    (VowpalWabbitMurmurWithPrefix.scala)."""

    def __init__(self, prefix: str):
        self.prefix = prefix.encode("utf-8")

    def hash(self, value: str, seed: int = 0) -> int:
        return murmurhash3_32(self.prefix + value.encode("utf-8"), seed)


def hash_features(tokens: Iterable[str], dim: int, seed: int = 0,
                  signed: bool = True) -> np.ndarray:
    """Hashing-trick bag-of-tokens -> dense vector of length ``dim``.

    ``signed`` applies the sign-bit convention (sign from one hash bit) so
    collisions cancel in expectation.
    """
    out = np.zeros(dim, dtype=np.float64)
    for t in tokens:
        h = murmurhash3_32(t, seed)
        idx = h % dim
        if signed:
            out[idx] += 1.0 if (h >> 31) & 1 == 0 else -1.0
        else:
            out[idx] += 1.0
    return out
