"""MurmurHash3 x86 32-bit — the hashing-trick primitive.

The reference's VW featurizer hashes feature names/values with murmur3,
with a pre-hashed-prefix optimization for column names
(reference: vw/src/main/scala/.../VowpalWabbitMurmurWithPrefix.scala:80,
VowpalWabbitFeaturizer.scala:150-165).  This is a NumPy re-implementation
with the same algorithm (public domain algorithm, Austin Appleby) and a
vectorized batch variant for hashing whole columns at once.
"""

from __future__ import annotations

from typing import Iterable, List, Union

import numpy as np

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl32(x: np.uint32, r: int) -> np.uint32:
    x = np.uint32(x)
    return np.uint32((np.uint64(x) << np.uint64(r) | np.uint64(x) >> np.uint64(32 - r)) & np.uint64(0xFFFFFFFF))


def murmurhash3_32(data: Union[bytes, str], seed: int = 0) -> int:
    """Scalar murmur3_x86_32 of a byte string."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    with np.errstate(over="ignore"):
        h = np.uint32(seed)
        n = len(data)
        nblocks = n // 4
        for i in range(nblocks):
            k = np.uint32(int.from_bytes(data[4 * i:4 * i + 4], "little"))
            k = np.uint32(k * _C1)
            k = _rotl32(k, 15)
            k = np.uint32(k * _C2)
            h = np.uint32(h ^ k)
            h = _rotl32(h, 13)
            h = np.uint32(h * np.uint32(5) + np.uint32(0xE6546B64))
        tail = data[nblocks * 4:]
        k = np.uint32(0)
        if len(tail) >= 3:
            k = np.uint32(k ^ np.uint32(tail[2]) << np.uint32(16))
        if len(tail) >= 2:
            k = np.uint32(k ^ np.uint32(tail[1]) << np.uint32(8))
        if len(tail) >= 1:
            k = np.uint32(k ^ np.uint32(tail[0]))
            k = np.uint32(k * _C1)
            k = _rotl32(k, 15)
            k = np.uint32(k * _C2)
            h = np.uint32(h ^ k)
        h = np.uint32(h ^ np.uint32(n))
        h = np.uint32(h ^ (h >> np.uint32(16)))
        h = np.uint32(h * np.uint32(0x85EBCA6B))
        h = np.uint32(h ^ (h >> np.uint32(13)))
        h = np.uint32(h * np.uint32(0xC2B2AE35))
        h = np.uint32(h ^ (h >> np.uint32(16)))
    return int(h)


class MurmurWithPrefix:
    """Hash ``prefix + value`` cheaply by pre-hashing the prefix blocks —
    the reference's trick for 'column-name + feature-value' hashes
    (VowpalWabbitMurmurWithPrefix.scala).  Correctness over cleverness:
    we cache the encoded prefix and concatenate; profiling shows the
    dominant cost on TPU pipelines is elsewhere."""

    def __init__(self, prefix: str):
        self.prefix = prefix.encode("utf-8")

    def hash(self, value: str, seed: int = 0) -> int:
        return murmurhash3_32(self.prefix + value.encode("utf-8"), seed)


def hash_features(tokens: Iterable[str], dim: int, seed: int = 0,
                  signed: bool = True) -> np.ndarray:
    """Hashing-trick bag-of-tokens -> dense vector of length ``dim``.

    ``signed`` applies the sign-bit convention (sign from one hash bit) so
    collisions cancel in expectation.
    """
    out = np.zeros(dim, dtype=np.float64)
    for t in tokens:
        h = murmurhash3_32(t, seed)
        idx = h % dim
        if signed:
            out[idx] += 1.0 if (h >> 31) & 1 == 0 else -1.0
        else:
            out[idx] += 1.0
    return out
