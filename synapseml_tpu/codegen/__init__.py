"""Multi-language binding codegen (reference L7: core/.../codegen/).

The reference mixes ``Wrappable`` into every stage and ``CodeGen.main``
(reference: codegen/CodeGen.scala:25, codegen/Wrappable.scala:52,369)
emits PySpark/R/.NET wrappers from Spark param metadata.  Here the param
metadata lives on :class:`~synapseml_tpu.core.params.Param` descriptors,
and the generators emit

- Python type stubs (``.pyi``) — IDE/typing surface for every stage,
- R wrappers over ``reticulate`` — one constructor function per stage,
- C# (.NET) wrapper classes over the Python.NET bridge shape,
- Markdown API docs — one page per module.

``generate_all(out_dir)`` is the ``sbt codegen`` analogue.
"""

from .discovery import discover_stages, load_all_modules
from .pygen import generate_pyi
from .rgen import generate_r
from .dotnetgen import generate_dotnet
from .docgen import generate_docs
from .testgen import generate_pytests
from .validate import validate_all


def generate_all(out_dir: str) -> dict:
    """Run every generator (reference: CodeGen.main + sbt codegen task,
    project/CodegenPlugin.scala:62-66).  Returns {language: [paths]}."""
    import os
    stages = discover_stages()
    return {
        "pyi": generate_pyi(stages, os.path.join(out_dir, "python")),
        "r": generate_r(stages, os.path.join(out_dir, "R")),
        "cs": generate_dotnet(stages, os.path.join(out_dir, "dotnet")),
        "docs": generate_docs(stages, os.path.join(out_dir, "docs")),
    }


__all__ = ["discover_stages", "load_all_modules", "generate_all",
           "generate_pyi", "generate_r", "generate_dotnet",
           "generate_docs", "generate_pytests", "validate_all"]
