"""Markdown API-doc generation (reference: the website docs are built
from the same Wrappable metadata — codegen/DocGen parts of
CodegenPlugin.scala).  One page per module: class, first doc line,
param table with types and defaults."""

from __future__ import annotations

import os
from collections import defaultdict
from typing import Dict, List

from .common import lang_types, public_params, py_default_repr
from .discovery import stage_kind


def _page(module: str, classes: List[type]) -> str:
    lines = [f"# `{module}`", ""]
    for cls in sorted(classes, key=lambda c: c.__name__):
        lines.append(f"## {cls.__name__} ({stage_kind(cls)})")
        doc = (cls.__doc__ or "").strip()
        if doc:
            lines.append("")
            lines.append(doc.splitlines()[0])
        params = public_params(cls)
        if params:
            lines += ["", "| param | type | default | doc |",
                      "|---|---|---|---|"]
            for p in params:
                pytype, _, _ = lang_types(p)
                doc_text = (p.doc or "").replace("|", "\\|")
                lines.append(f"| `{p.name}` | `{pytype}` | "
                             f"`{py_default_repr(p)}` | {doc_text} |")
        lines.append("")
    return "\n".join(lines)


def generate_docs(stages: Dict[str, type], out_dir: str) -> List[str]:
    os.makedirs(out_dir, exist_ok=True)
    by_module = defaultdict(list)
    for qual, cls in stages.items():
        by_module[cls.__module__].append(cls)
    paths = []
    index = ["# synapseml_tpu API reference", "",
             "Generated from stage param metadata; regenerate with:", "",
             "    python -c \"from synapseml_tpu.codegen import "
             "discover_stages, generate_docs; "
             "generate_docs(discover_stages(), 'docs/api')\"", ""]
    for module, classes in sorted(by_module.items()):
        fname = module.replace("synapseml_tpu.", "").replace(".", "_") + ".md"
        path = os.path.join(out_dir, fname)
        with open(path, "w") as f:
            f.write(_page(module, classes))
        index.append(f"- [`{module}`]({fname}) — "
                     f"{len(classes)} stages")
        paths.append(path)
    # hand-maintained (non-stage) pages already in out_dir survive
    # regeneration and self-register in the index: anything *.md the
    # generator did not just write gets linked with its first-heading
    # one-liner (previously these links were manual post-edits that every
    # regeneration silently wiped)
    import re
    generated = {os.path.basename(p) for p in paths} | {"index.md"}
    #: a generated page's first line is exactly "# `<module>`" — a file
    #: matching it but absent from this run is a STALE generated page
    #: (its stage module was removed/renamed), not a hand-maintained one
    _generated_head = re.compile(r"^# `[\w.]+`$")
    manual = []
    for fname in sorted(os.listdir(out_dir)):
        if not fname.endswith(".md") or fname in generated:
            continue
        title = fname[:-3]
        try:
            with open(os.path.join(out_dir, fname)) as f:
                first = f.readline().rstrip("\n")
        except OSError:
            first = ""
        if _generated_head.match(first.strip()):
            continue                      # stale generated page: skip
        if first.lstrip("#").strip():
            title = first.lstrip("#").strip()
        manual.append((title, fname))
    if manual:
        index += ["", "Hand-maintained (non-stage) module pages:", ""]
        for title, fname in manual:
            index.append(f"- [{title}]({fname})")
    index_path = os.path.join(out_dir, "index.md")
    with open(index_path, "w") as f:
        f.write("\n".join(index) + "\n")
    paths.append(index_path)
    return paths
