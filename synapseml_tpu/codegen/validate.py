"""Generated-binding validation: every artifact is executed or
structurally cross-checked against the live stage registry.

The reference mechanically TESTS its generated wrappers (reference:
core/src/test/scala/com/microsoft/azure/synapse/ml/core/test/fuzzing/
Fuzzing.scala:263,428 emit Python/R/.NET test files from the same
TestObjects; sbt ``testgen``, project/CodegenPlugin.scala:63).  Round 2's
wrappers were write-only — syntactically broken output kept the suite
green.  These validators close that: ``.pyi`` stubs must compile, R and
C# wrappers must parse structurally AND agree with the real classes'
param surfaces (names, setters, import paths), so a generator regression
fails the suite.

No R interpreter or .NET SDK ships in the build image, so R/C# checks
are structural (delimiter balance, declaration extraction) plus registry
cross-checks — which is exactly the class of breakage a generator can
introduce (wrong names, wrong defaults, unbalanced emission, stale
import paths).
"""

from __future__ import annotations

import importlib
import re
from typing import Dict, Iterable, List

from .common import public_params
from .dotnetgen import _cs_name
from .rgen import _snake


class GeneratedArtifactError(AssertionError):
    """A generated binding failed validation."""


def _check_balanced(src: str, path: str, pairs: str = "(){}[]",
                    comment: str = "#") -> None:
    # doc comments carry prose (apostrophes, smileys) — strip them so the
    # tracker only sees code
    src = "\n".join(line for line in src.splitlines()
                    if not line.lstrip().startswith(comment))
    openers = {pairs[i]: pairs[i + 1] for i in range(0, len(pairs), 2)}
    closers = {v: k for k, v in openers.items()}
    stack: List[str] = []
    in_str = None
    prev = ""
    for ch in src:
        if in_str:
            if ch == in_str and prev != "\\":
                in_str = None
        elif ch in "\"'":
            in_str = ch
        elif ch in openers:
            stack.append(ch)
        elif ch in closers:
            if not stack or stack.pop() != closers[ch]:
                raise GeneratedArtifactError(
                    f"{path}: unbalanced {ch!r}")
        prev = ch
    if stack:
        raise GeneratedArtifactError(f"{path}: unclosed {stack[-1]!r}")


def validate_pyi(paths: Iterable[str]) -> int:
    """Compile every stub — a stub that does not compile is broken."""
    n = 0
    for path in paths:
        src = open(path).read()
        compile(src, path, "exec")
        n += 1
    return n


_R_FUNC_RE = re.compile(
    r"^(sml_[a-z0-9_]+) <- function\((.*)\) \{$", re.MULTILINE)


def _r_arg_names(arglist: str) -> List[str]:
    """Argument names from an R formal list, respecting quoted defaults
    (a default like \"(a, b)\" must not split the list)."""
    names, depth, in_str, start = [], 0, None, 0
    prev = ""

    def take(segment: str) -> None:
        seg = segment.strip()
        if seg:
            names.append(seg.split("=")[0].strip())

    for i, ch in enumerate(arglist):
        if in_str:
            if ch == in_str and prev != "\\":
                in_str = None
        elif ch in "\"'":
            in_str = ch
        elif ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        elif ch == "," and depth == 0:
            take(arglist[start:i])
            start = i + 1
        prev = ch
    take(arglist[start:])
    return names
_R_IMPORT_RE = re.compile(r'reticulate::import\("([^"]+)"\)')
_R_CALL_RE = re.compile(r"do\.call\(mod\$([A-Za-z0-9_]+),")


def validate_r(paths: Iterable[str], stages: Dict[str, type]) -> int:
    """Structural + registry cross-check of the R wrappers.

    Every stage must have exactly one constructor function whose argument
    NAMES equal the stage's public params in order, whose
    ``reticulate::import`` target is an importable module holding the
    class, and whose file balances its delimiters."""
    by_fname = {"sml_" + _snake(cls.__name__): cls
                for cls in stages.values()}
    seen = set()
    for path in paths:
        src = open(path).read()
        _check_balanced(src, path)
        funcs = _R_FUNC_RE.findall(src)
        imports = _R_IMPORT_RE.findall(src)
        calls = _R_CALL_RE.findall(src)
        if not funcs:
            raise GeneratedArtifactError(f"{path}: no constructor functions")
        if len(funcs) != len(imports) or len(funcs) != len(calls):
            raise GeneratedArtifactError(
                f"{path}: {len(funcs)} functions vs {len(imports)} imports "
                f"vs {len(calls)} constructor calls")
        for (fname, args), module, clsname in zip(funcs, imports, calls):
            cls = by_fname.get(fname)
            if cls is None:
                raise GeneratedArtifactError(
                    f"{path}: {fname} matches no registered stage")
            expected = [p.name for p in public_params(cls)]
            got = _r_arg_names(args)
            if got != expected:
                raise GeneratedArtifactError(
                    f"{path}: {fname} args {got} != params {expected}")
            mod = importlib.import_module(module)
            if getattr(mod, clsname, None) is not cls:
                raise GeneratedArtifactError(
                    f"{path}: {fname} constructs {module}.{clsname}, which "
                    "is not the registered class")
            seen.add(fname)
    missing = set(by_fname) - seen
    if missing:
        raise GeneratedArtifactError(
            f"stages without R wrappers: {sorted(missing)[:5]}...")
    return len(seen)


def validate_dotnet(paths: Iterable[str], stages: Dict[str, type]) -> int:
    """Structural + registry cross-check of the C# wrappers: every stage
    class extends PythonStage with its module/qualname constructor and one
    typed setter per param; the runtime base class ships alongside."""
    sources = {p: open(p).read() for p in paths}
    joined = "\n".join(sources.values())
    for path, src in sources.items():
        _check_balanced(src, path, "{}()", comment="//")
    if "public abstract class PythonStage" not in joined:
        raise GeneratedArtifactError(
            "the PythonStage runtime base is missing from the generated "
            "output — wrappers would not compile")
    for cls in stages.values():
        decl = f"public class {cls.__name__} : PythonStage"
        if decl not in joined:
            raise GeneratedArtifactError(
                f"missing C# class for {cls.__name__}")
        ctor = f'base("{cls.__module__}", "{cls.__qualname__}")'
        if ctor not in joined:
            raise GeneratedArtifactError(
                f"{cls.__name__}: constructor does not reference "
                f"{cls.__module__}.{cls.__qualname__}")
        for p in public_params(cls):
            setter = f"public {cls.__name__} Set{_cs_name(p.name)}("
            if setter not in joined:
                raise GeneratedArtifactError(
                    f"{cls.__name__}: missing setter for param {p.name}")
    return len(stages)


def validate_all(outputs: Dict[str, List[str]],
                 stages: Dict[str, type]) -> Dict[str, int]:
    return {
        "pyi": validate_pyi(outputs["pyi"]),
        "r": validate_r(outputs["r"], stages),
        "cs": validate_dotnet(outputs["cs"], stages),
    }
