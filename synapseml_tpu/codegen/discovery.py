"""Stage discovery: import every package module, read the registry.

The reference reflects over the jar for all ``Wrappable`` classes
(reference: core/utils/JarLoadingUtils.scala — ``instantiateServices``);
here we walk ``synapseml_tpu``'s module tree, import everything, and
collect the stage registry that ``PipelineStage.__init_subclass__``
populates (core/pipeline.py).
"""

from __future__ import annotations

import importlib
import pkgutil
from typing import Dict, List, Type

#: modules that require optional/native context and are skipped in codegen
_SKIP_PREFIXES = ("synapseml_tpu.native",)


def load_all_modules() -> List[str]:
    """Import every synapseml_tpu submodule; return imported names."""
    import synapseml_tpu
    loaded = []
    for info in pkgutil.walk_packages(synapseml_tpu.__path__,
                                      prefix="synapseml_tpu."):
        if info.name.startswith(_SKIP_PREFIXES):
            continue
        importlib.import_module(info.name)
        loaded.append(info.name)
    return loaded


def discover_stages() -> Dict[str, type]:
    """qualified-name → stage class for every public, concrete stage."""
    from ..core.pipeline import (_STAGE_REGISTRY, Estimator, Model,
                                 Pipeline, PipelineModel, PipelineStage,
                                 Transformer)
    load_all_modules()
    base = {Transformer, Estimator, Model, PipelineStage,
            Pipeline, PipelineModel}
    out: Dict[str, type] = {}
    for qual, cls in sorted(_STAGE_REGISTRY.items()):
        if cls in base:
            continue
        if cls.__name__.startswith("_"):
            continue  # private helper bases
        if not cls.__module__.startswith("synapseml_tpu."):
            continue  # stages defined in tests/user code are not ours to wrap
        out[qual] = cls
    return out


def stage_kind(cls: type) -> str:
    """'estimator' | 'model' | 'transformer' (drives wrapper shape)."""
    from ..core.pipeline import Estimator, Model, Transformer
    if issubclass(cls, Estimator):
        return "estimator"
    if issubclass(cls, Model):
        return "model"
    if issubclass(cls, Transformer):
        return "transformer"
    return "stage"
