"""Shared param-metadata helpers for the generators (reference:
codegen/DefaultParamInfo.scala — maps each param type to per-language
type names and default renderings)."""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from ..core.params import (ArrayParam, BoolParam, ComplexParam, DictParam,
                           FloatParam, IntParam, ListParam, Param,
                           StringParam)


def public_params(cls: type) -> List[Param]:
    """Declared params, inheritance-ordered, skipping private names."""
    seen: Dict[str, Param] = {}
    for klass in reversed(cls.__mro__):
        for key, val in vars(klass).items():
            if isinstance(val, Param) and not val.name.startswith("_"):
                seen[val.name] = val
    return list(seen.values())


#: Param class → (python type, R roxygen type, C# type)
_TYPE_MAP: List[Tuple[type, Tuple[str, str, str]]] = [
    (IntParam, ("int", "integer", "int")),
    (FloatParam, ("float", "numeric", "double")),
    (BoolParam, ("bool", "logical", "bool")),
    (StringParam, ("str", "character", "string")),
    (ListParam, ("list", "list", "object[]")),
    (ArrayParam, ("numpy.ndarray", "numeric vector", "double[]")),
    (DictParam, ("dict", "named list", "Dictionary<string, object>")),
    (ComplexParam, ("typing.Any", "object", "object")),
]


def lang_types(p: Param) -> Tuple[str, str, str]:
    for klass, names in _TYPE_MAP:
        if isinstance(p, klass):
            return names
    return ("typing.Any", "object", "object")


def py_default_repr(p: Param) -> str:
    d = p.default
    if d is None or isinstance(d, (int, float, bool, str)):
        return repr(d)
    return "..."
