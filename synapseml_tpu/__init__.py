"""synapseml_tpu — a TPU-native framework with the capabilities of SynapseML.

Re-designed from scratch for JAX/XLA/Pallas on TPU: DataFrame-level
``.fit()/.transform()`` pipelines whose execution backend is jit-compiled XLA
over a ``jax.sharding.Mesh`` — histogram GBDT with Pallas kernels + ICI
``psum`` allreduce instead of LightGBM's socket ring, pjit data/tensor
parallel deep learning instead of Horovod/NCCL, ONNX→XLA lowering instead of
ONNX Runtime sessions, and partition→chip placement instead of Spark
executor→GPU placement.
"""

__version__ = "0.1.0"

from .core.dataset import Dataset
from .core.params import Params
from .core.pipeline import (Estimator, Evaluator, Model, Pipeline,
                            PipelineModel, PipelineStage, Transformer)

__all__ = [
    "Dataset", "Params", "Estimator", "Evaluator", "Model", "Pipeline",
    "PipelineModel", "PipelineStage", "Transformer", "__version__",
]
