"""synapseml_tpu — a TPU-native framework with the capabilities of SynapseML.

Re-designed from scratch for JAX/XLA/Pallas on TPU: DataFrame-level
``.fit()/.transform()`` pipelines whose execution backend is jit-compiled XLA
over a ``jax.sharding.Mesh`` — histogram GBDT with Pallas kernels + ICI
``psum`` allreduce instead of LightGBM's socket ring, pjit data/tensor
parallel deep learning instead of Horovod/NCCL, ONNX→XLA lowering instead of
ONNX Runtime sessions, and partition→chip placement instead of Spark
executor→GPU placement.
"""

__version__ = "0.1.0"

import os as _os

# Persistent XLA compilation cache: first-compile of the jitted training
# steps costs tens of seconds on TPU; caching compiled executables on disk
# makes every later process (bench runs, notebooks, serving restarts) start
# warm.  Opt out with SYNAPSEML_TPU_NO_COMPILE_CACHE=1.
if not _os.environ.get("SYNAPSEML_TPU_NO_COMPILE_CACHE"):
    _cache = _os.path.join(_os.path.expanduser("~"), ".cache",
                           "synapseml_tpu", "xla_cache")
    _os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache)
    _os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    try:
        # if jax was imported before us its config already snapshotted the
        # env — set the live config too (works regardless of import order)
        import jax as _jax
        if _jax.config.jax_compilation_cache_dir is None:
            _jax.config.update("jax_compilation_cache_dir",
                               _os.environ["JAX_COMPILATION_CACHE_DIR"])
            _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:  # never let cache setup break import
        pass

from .core.dataset import Dataset
from .core.params import Params
from .core.pipeline import (Estimator, Evaluator, Model, Pipeline,
                            PipelineModel, PipelineStage, Transformer)

__all__ = [
    "Dataset", "Params", "Estimator", "Evaluator", "Model", "Pipeline",
    "PipelineModel", "PipelineStage", "Transformer", "__version__",
]
