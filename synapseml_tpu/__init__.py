"""synapseml_tpu — a TPU-native framework with the capabilities of SynapseML.

Re-designed from scratch for JAX/XLA/Pallas on TPU: DataFrame-level
``.fit()/.transform()`` pipelines whose execution backend is jit-compiled XLA
over a ``jax.sharding.Mesh`` — histogram GBDT with Pallas kernels + ICI
``psum`` allreduce instead of LightGBM's socket ring, pjit data/tensor
parallel deep learning instead of Horovod/NCCL, ONNX→XLA lowering instead of
ONNX Runtime sessions, and partition→chip placement instead of Spark
executor→GPU placement.
"""

__version__ = "0.1.0"

import os as _os

# Persistent XLA compilation cache: first-compile of the jitted training
# steps costs tens of seconds on TPU; caching compiled executables on disk
# makes every later process (bench runs, notebooks, serving restarts) start
# warm.  Opt out with SYNAPSEML_TPU_NO_COMPILE_CACHE=1.
if not _os.environ.get("SYNAPSEML_TPU_NO_COMPILE_CACHE"):
    _cache = _os.path.join(_os.path.expanduser("~"), ".cache",
                           "synapseml_tpu", "xla_cache")
    _os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache)
    _os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    try:
        # if jax was imported before us its config already snapshotted the
        # env — set the live config too (works regardless of import order)
        import jax as _jax
        if _jax.config.jax_compilation_cache_dir is None:
            _jax.config.update("jax_compilation_cache_dir",
                               _os.environ["JAX_COMPILATION_CACHE_DIR"])
            _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:  # never let cache setup break import
        pass

# jax version compat: the codebase targets the modern top-level
# ``jax.shard_map(f, mesh=..., in_specs=..., out_specs=..., check_vma=...)``;
# on older jax that API lives at jax.experimental.shard_map with the
# ``check_rep`` spelling — install an adapter so both environments work.
# Deliberately a patch on the jax module (not an internal wrapper): the
# package's call sites AND its test suite spell ``jax.shard_map``, and the
# patch only installs where the modern name does not exist at all, so
# modern environments are untouched.  Known tradeoff: on old jax, other
# code in the process feature-detecting ``jax.shard_map`` will find this
# adapter, which disables the (false-positive-prone) check_rep pass.
try:
    import jax as _jax
    if not hasattr(_jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map_impl

        def _shard_map_compat(f, *, mesh, in_specs, out_specs,
                              check_vma=True, **kw):
            # old jax's check_rep has known false positives (e.g. scan
            # carries under psum; its own error message suggests
            # check_rep=False) — the modern check_vma flag has no faithful
            # equivalent, so the compat path always disables the check
            del check_vma
            return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_rep=False,
                                   **kw)

        _jax.shard_map = _shard_map_compat
    if not hasattr(_jax.lax, "axis_size"):
        # lax.psum of a Python-int literal constant-folds to the concrete
        # axis size — the documented pre-axis_size idiom
        _jax.lax.axis_size = lambda axis_name: _jax.lax.psum(1, axis_name)
except Exception:  # pragma: no cover - jax absent/newer layout
    pass

from . import resilience, telemetry
from .core.dataset import Dataset
from .core.params import Params
from .core.pipeline import (Estimator, Evaluator, Model, Pipeline,
                            PipelineModel, PipelineStage, Transformer)
from .resilience import (CircuitBreaker, Deadline, RetryPolicy, get_faults)
from .telemetry import get_registry, span

__all__ = [
    "Dataset", "Params", "Estimator", "Evaluator", "Model", "Pipeline",
    "PipelineModel", "PipelineStage", "Transformer", "__version__",
    "telemetry", "get_registry", "span",
    "resilience", "RetryPolicy", "Deadline", "CircuitBreaker", "get_faults",
]
