"""synapseml_tpu — a TPU-native framework with the capabilities of SynapseML.

Re-designed from scratch for JAX/XLA/Pallas on TPU: DataFrame-level
``.fit()/.transform()`` pipelines whose execution backend is jit-compiled XLA
over a ``jax.sharding.Mesh`` — histogram GBDT with Pallas kernels + ICI
``psum`` allreduce instead of LightGBM's socket ring, pjit data/tensor
parallel deep learning instead of Horovod/NCCL, ONNX→XLA lowering instead of
ONNX Runtime sessions, and partition→chip placement instead of Spark
executor→GPU placement.
"""

__version__ = "0.1.0"

import os as _os

# Persistent XLA compilation cache: first-compile of the jitted training
# steps costs tens of seconds on TPU; caching compiled executables on disk
# makes every later process (bench runs, notebooks, serving restarts) start
# warm.  Opt out with SYNAPSEML_TPU_NO_COMPILE_CACHE=1.
if not _os.environ.get("SYNAPSEML_TPU_NO_COMPILE_CACHE"):
    _cache = _os.path.join(_os.path.expanduser("~"), ".cache",
                           "synapseml_tpu", "xla_cache")
    _os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache)
    _os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    try:
        # if jax was imported before us its config already snapshotted the
        # env — set the live config too (works regardless of import order)
        import jax as _jax
        if _jax.config.jax_compilation_cache_dir is None:
            _jax.config.update("jax_compilation_cache_dir",
                               _os.environ["JAX_COMPILATION_CACHE_DIR"])
            _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:  # never let cache setup break import
        pass

# jax version compat: the codebase targets the modern top-level
# ``jax.shard_map(f, mesh=..., in_specs=..., out_specs=..., check_vma=...)``;
# on older jax that API lives at jax.experimental.shard_map with the
# ``check_rep`` spelling — install an adapter so both environments work.
# Deliberately a patch on the jax module (not an internal wrapper): the
# package's call sites AND its test suite spell ``jax.shard_map``, and the
# patch only installs where the modern name does not exist at all, so
# modern environments are untouched.  Known tradeoff: on old jax, other
# code in the process feature-detecting ``jax.shard_map`` will find this
# adapter, which disables the (false-positive-prone) check_rep pass.
try:
    import jax as _jax
    if not hasattr(_jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map_impl

        def _shard_map_compat(f, *, mesh, in_specs, out_specs,
                              check_vma=True, **kw):
            # old jax's check_rep has known false positives (e.g. scan
            # carries under psum; its own error message suggests
            # check_rep=False) — the modern check_vma flag has no faithful
            # equivalent, so the compat path always disables the check
            del check_vma
            return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_rep=False,
                                   **kw)

        _jax.shard_map = _shard_map_compat
    if not hasattr(_jax.lax, "axis_size"):
        # lax.psum of a Python-int literal constant-folds to the concrete
        # axis size — the documented pre-axis_size idiom
        _jax.lax.axis_size = lambda axis_name: _jax.lax.psum(1, axis_name)
except Exception:  # pragma: no cover - jax absent/newer layout
    pass

# flax compat: ``nn.with_partitioning`` boxes params with LOGICAL axis
# names ("embed", "heads", "vocab", ...) that the trainers translate to
# mesh axes through ``nn.logical_axis_rules`` at the jit boundary.  flax
# 0.10's ``Partitioned.unbox`` applies the RAW names as a sharding
# constraint whenever a global mesh is active — tracing any apply under
# ``with mesh:`` then raises "Resource axis 'vocab' not found in mesh"
# (the env failure carried since PR 3: DL text fits + llm TP forward on
# this container).  The shim routes unbox's constraint through the
# ACTIVE logical axis rules: names the rules (or the mesh itself) know
# keep their mapping, unknown names mean "no constraint on this dim" —
# exactly the semantics ``DLTrainer`` already sets up via
# ``nn.logical_axis_rules(usable_rules(mesh))``.  Gated on the buggy
# behavior being present so fixed flax versions are untouched.
try:
    import flax as _flax
    import jax as _jax
    from flax.core import meta as _flax_meta
    from flax.linen import spmd as _flax_spmd

    # version-ceiling gate: the raw-name constraint exists through flax
    # 0.10.x; newer majors/minors are assumed fixed (or different enough
    # that this shim must be re-validated, not silently kept)
    _flax_ver = tuple(int(x) for x in _flax.__version__.split(".")[:2])
    if _flax_ver <= (0, 10) \
            and "logical" not in (_flax_meta.Partitioned.unbox.__doc__
                                  or ""):
        _orig_unbox = _flax_meta.Partitioned.unbox

        def _unbox_logical(self, apply_constraint=True):
            """Returns the wrapped value; the partitioning constraint is
            applied through the active logical axis rules (compat shim —
            translates logical names, drops unmapped ones)."""
            try:
                if not (apply_constraint and
                        (_flax_meta._global_mesh_defined()
                         or self.mesh is not None)):
                    return self.value
                mesh = self.mesh
                if mesh is None:
                    env = _jax._src.mesh.thread_resources.env
                    mesh = env.physical_mesh
                axes = set(getattr(mesh, "axis_names", ()) or ())
                rules = dict(_flax_spmd.get_logical_axis_rules() or ())

                def to_mesh(name):
                    if name is None or name in axes:
                        return name
                    mapped = rules.get(name)
                    return mapped if mapped in axes else None

                spec = _jax.sharding.PartitionSpec(
                    *(tuple(to_mesh(n) for n in ns)
                      if isinstance(ns, tuple) else to_mesh(ns)
                      for ns in self.names))
                if self.mesh is not None:
                    return _jax.lax.with_sharding_constraint(
                        self.value,
                        _jax.sharding.NamedSharding(self.mesh, spec))
                return _jax.lax.with_sharding_constraint(self.value, spec)
            except Exception:
                # fail SOFT: the constraint is a layout hint — a private
                # API moving under us must degrade to "unconstrained",
                # never to a trace-time crash in every DL fit
                return self.value

        _flax_meta.Partitioned.unbox = _unbox_logical
except Exception:  # pragma: no cover - flax absent/fixed layout
    pass

from . import resilience, telemetry
from .core.dataset import Dataset
from .core.params import Params
from .core.pipeline import (Estimator, Evaluator, Model, Pipeline,
                            PipelineModel, PipelineStage, Transformer)
from .resilience import (CircuitBreaker, Deadline, RetryPolicy, get_faults)
from .telemetry import get_registry, span

__all__ = [
    "Dataset", "Params", "Estimator", "Evaluator", "Model", "Pipeline",
    "PipelineModel", "PipelineStage", "Transformer", "__version__",
    "telemetry", "get_registry", "span",
    "resilience", "RetryPolicy", "Deadline", "CircuitBreaker", "get_faults",
]
