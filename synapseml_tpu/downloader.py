"""Pretrained-model downloader (reference: core/src/main/python/synapse/
ml/downloader/ModelDownloader.py:93-169 + the Scala side it wraps,
core/.../downloader/ — manifest of ModelSchema entries, sha256-verified
downloads into a local cache).

The TPU build keeps the same surface (``localModels`` / ``remoteModels``
/ ``downloadByName`` / ``downloadModel(s)``) with a JSON manifest served
over HTTP or present on disk; no JVM, no Spark session."""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import asdict, dataclass, field
from typing import Iterator, List, Optional

from .io.http import HTTPClient, HTTPRequestData


@dataclass
class ModelSchema:
    """One downloadable model (reference: ModelDownloader.py:15-51)."""

    name: str
    dataset: str = ""
    modelType: str = ""
    uri: str = ""
    hash: str = ""
    size: int = 0
    inputNode: int = 0
    numLayers: int = 0
    layerNames: List[str] = field(default_factory=list)

    def __repr__(self):
        return (f"ModelSchema<name: {self.name}, dataset: {self.dataset}, "
                f"loc: {self.uri}>")


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class ModelDownloader:
    """Manifest-driven model cache (reference: ModelDownloader.py:93).

    ``server_url`` points at a directory serving ``manifest.json`` plus
    the model files; with no egress it can also be a local ``file://``
    directory path."""

    MANIFEST = "manifest.json"

    def __init__(self, local_path: str, server_url: str = ""):
        self.local_path = local_path
        self.server_url = server_url.rstrip("/")
        os.makedirs(local_path, exist_ok=True)
        self._http = HTTPClient()

    # -- listing -----------------------------------------------------------
    def localModels(self) -> Iterator[ModelSchema]:
        """Models already present + verified in the cache."""
        man = os.path.join(self.local_path, self.MANIFEST)
        if not os.path.exists(man):
            return
        with open(man) as f:
            entries = json.load(f)
        for e in entries:
            schema = ModelSchema(**e)
            target = self._target(schema)
            if os.path.exists(target):
                yield schema

    def remoteModels(self) -> Iterator[ModelSchema]:
        """Models listed by the server's manifest."""
        raw = self._fetch(self.MANIFEST)
        for e in json.loads(raw.decode()):
            yield ModelSchema(**e)

    # -- downloading -------------------------------------------------------
    def downloadModel(self, model: ModelSchema) -> ModelSchema:
        target = self._target(model)
        if not (os.path.exists(target) and
                (not model.hash or _sha256(target) == model.hash)):
            data = self._fetch(model.uri or model.name)
            with open(target, "wb") as f:
                f.write(data)
            if model.hash and _sha256(target) != model.hash:
                os.remove(target)
                raise ValueError(
                    f"hash mismatch for model {model.name}")
        self._record(model)
        out = ModelSchema(**asdict(model))
        out.uri = target
        return out

    def downloadByName(self, name: str) -> ModelSchema:
        for m in self.remoteModels():
            if m.name == name:
                return self.downloadModel(m)
        raise KeyError(f"model {name!r} not in remote manifest")

    def downloadModels(self, models: Optional[List[ModelSchema]] = None
                       ) -> List[ModelSchema]:
        if models is None:
            models = list(self.remoteModels())
        return [self.downloadModel(m) for m in models]

    # -- internals ---------------------------------------------------------
    def _target(self, model: ModelSchema) -> str:
        base = os.path.basename(model.uri or model.name) or model.name
        return os.path.join(self.local_path, base)

    def _record(self, model: ModelSchema) -> None:
        man = os.path.join(self.local_path, self.MANIFEST)
        entries = []
        if os.path.exists(man):
            with open(man) as f:
                entries = json.load(f)
        entries = [e for e in entries if e.get("name") != model.name]
        entries.append(asdict(model))
        with open(man, "w") as f:
            json.dump(entries, f, indent=1)

    def _fetch(self, rel: str) -> bytes:
        if rel.startswith(("http://", "https://")):
            url = rel
        elif self.server_url.startswith(("http://", "https://")):
            url = f"{self.server_url}/{rel}"
        else:
            # local directory server
            path = rel if os.path.isabs(rel) else os.path.join(
                self.server_url, rel)
            with open(path, "rb") as f:
                return f.read()
        resp = self._http.send(HTTPRequestData(url=url, method="GET"))
        if resp.status_code != 200:
            raise IOError(f"fetch {url} failed: "
                          f"{resp.status_code} {resp.reason}")
        return resp.entity
