"""Native runtime bindings.

The reference ships native engines inside jars and extracts them at
runtime (reference: core/env/NativeLoader.java:28-90 — jar → tmpdir →
``System.load``).  The analogue here: the C++ loader compiles ON FIRST
USE with the toolchain baked into the image (``g++ -O3 -shared``) into a
per-user cache directory keyed by source hash, then binds over ctypes —
no wheel step, no pybind11.  Every entry point has a numpy fallback so
the framework degrades gracefully where a toolchain is absent.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "loader.cpp")
_TEXT_SRC = os.path.join(os.path.dirname(__file__), "textproc.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False
_TEXTLIB: Optional[ctypes.CDLL] = None
_TEXTLIB_FAILED = False


def _cache_dir() -> str:
    root = os.environ.get("SYNAPSEML_TPU_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "synapseml_tpu", "native")
    os.makedirs(root, exist_ok=True)
    return root


def _compile_source(src_path: str, stem: str) -> Optional[str]:
    with open(src_path, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"lib{stem}_{tag}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           src_path, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
    except (OSError, subprocess.SubprocessError):
        return None
    os.replace(tmp, out)
    return out


def _get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        path = _compile_source(_SRC, "smlloader")
        if path is None:
            _LIB_FAILED = True
            return None
        lib = ctypes.CDLL(path)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.sml_csv_dims.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_char, i64p, i64p]
        lib.sml_csv_dims.restype = ctypes.c_int
        lib.sml_csv_read_f32.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                         ctypes.c_char, ctypes.c_int64,
                                         ctypes.c_int64, f32p, ctypes.c_int]
        lib.sml_csv_read_f32.restype = ctypes.c_int
        lib.sml_colstore_write.argtypes = [ctypes.c_char_p, f32p,
                                           ctypes.c_int64, ctypes.c_int64]
        lib.sml_colstore_write.restype = ctypes.c_int
        lib.sml_colstore_dims.argtypes = [ctypes.c_char_p, i64p, i64p]
        lib.sml_colstore_dims.restype = ctypes.c_int
        lib.sml_colstore_read.argtypes = [ctypes.c_char_p, f32p,
                                          ctypes.c_int64, ctypes.c_int64]
        lib.sml_colstore_read.restype = ctypes.c_int
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.sml_bin_u8.argtypes = [f32p, ctypes.c_int64, ctypes.c_int64,
                                   f32p, ctypes.c_int64, u8p, ctypes.c_int]
        lib.sml_bin_u8.restype = ctypes.c_int
        _LIB = lib
        return _LIB


def native_available() -> bool:
    return _get_lib() is not None


def _read_header(path: str, delim: str) -> Tuple[bool, list]:
    with open(path, "r", errors="replace") as f:
        first = f.readline().rstrip("\r\n")
    fields = first.split(delim)

    def numeric(s: str) -> bool:
        try:
            float(s)
            return True
        except ValueError:
            return s.strip() == ""

    has_header = not all(numeric(x) for x in fields)
    names = (fields if has_header
             else [f"f{i}" for i in range(len(fields))])
    return has_header, names


def read_csv_matrix(path: str, delim: str = ",",
                    n_threads: int = 0) -> Tuple[np.ndarray, list]:
    """(rows, cols) float32 matrix + column names.  Native path: mmap +
    multithreaded parse; fallback: numpy.genfromtxt."""
    has_header, names = _read_header(path, delim)
    lib = _get_lib()
    if lib is not None:
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        rc = lib.sml_csv_dims(path.encode(), int(has_header),
                              delim.encode(), ctypes.byref(rows),
                              ctypes.byref(cols))
        if rc == 0:
            r, c = rows.value, cols.value
            out = np.empty((c, r), np.float32)  # column-major blocks
            rc = lib.sml_csv_read_f32(
                path.encode(), int(has_header), delim.encode(), r, c,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                int(n_threads))
            if rc >= 0:
                return out.T, names[:c]
    mat = np.genfromtxt(path, delimiter=delim,
                        skip_header=1 if has_header else 0,
                        dtype=np.float32, ndmin=2)
    return mat, names[:mat.shape[1]]


def bin_columns_u8(features: np.ndarray, upper_bounds: np.ndarray,
                   max_bin: int, n_threads: int = 0) -> np.ndarray:
    """Quantile-bin raw (n, F) float32 features → (n, F) uint8 bins
    (NaN → 0, content bins 1..max_bin).  Native path: row-blocked
    multithreaded binary search; fallback: threaded numpy searchsorted.
    The uint8 result is the array shipped to the device — 4× less
    host→device traffic than raw floats."""
    if not 1 <= max_bin <= 255:
        raise ValueError(
            f"bin_columns_u8 requires max_bin in [1, 255], got {max_bin}; "
            "use BinMapper.transform (int32) for wider bin ranges")
    features = np.ascontiguousarray(features, np.float32)
    upper_bounds = np.ascontiguousarray(upper_bounds, np.float32)
    n, f = features.shape
    out = np.empty((n, f), np.uint8)
    lib = _get_lib()
    if lib is not None:
        rc = lib.sml_bin_u8(
            features.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n, f,
            upper_bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            max_bin, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            int(n_threads))
        if rc == 0:
            return out

    def one(j):
        col = features[:, j]
        idx = np.searchsorted(upper_bounds[j, :max_bin], col, side="left")
        b = np.minimum(idx, max_bin - 1).astype(np.uint8) + 1
        b[np.isnan(col)] = 0
        out[:, j] = b

    from concurrent.futures import ThreadPoolExecutor
    if n * f > 1 << 20:
        with ThreadPoolExecutor() as pool:
            list(pool.map(one, range(f)))
    else:
        for j in range(f):
            one(j)
    return out


def write_colstore(path: str, matrix: np.ndarray) -> None:
    m = np.ascontiguousarray(np.asarray(matrix, np.float32).T)  # col blocks
    lib = _get_lib()
    if lib is not None:
        rc = lib.sml_colstore_write(
            path.encode(), m.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            matrix.shape[0], matrix.shape[1])
        if rc == 0:
            return
    with open(path, "wb") as f:
        f.write(b"SMLC")
        f.write(np.uint32(1).tobytes())
        f.write(np.int64(matrix.shape[0]).tobytes())
        f.write(np.int64(matrix.shape[1]).tobytes())
        f.write(m.tobytes())


def read_colstore(path: str) -> np.ndarray:
    lib = _get_lib()
    if lib is not None:
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        if lib.sml_colstore_dims(path.encode(), ctypes.byref(rows),
                                 ctypes.byref(cols)) == 0:
            out = np.empty((cols.value, rows.value), np.float32)
            if lib.sml_colstore_read(
                    path.encode(),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    rows.value, cols.value) == 0:
                return out.T
    with open(path, "rb") as f:
        magic = f.read(4)
        if magic != b"SMLC":
            raise IOError(f"{path}: not an SMLC column store")
        version = int(np.frombuffer(f.read(4), np.uint32)[0])
        rows = int(np.frombuffer(f.read(8), np.int64)[0])
        cols = int(np.frombuffer(f.read(8), np.int64)[0])
        if version == 1:
            data = np.frombuffer(f.read(rows * cols * 4), np.float32)
        elif version == 2:
            # v2 stores bf16 bit patterns (io.colstore.write_matrix
            # dtype="bf16"); upcast exactly like ChunkedColumnSource
            from ..io.colstore import bf16_bits_to_f32
            data = bf16_bits_to_f32(
                np.frombuffer(f.read(rows * cols * 2), np.uint16))
        else:
            raise IOError(f"{path}: unknown SMLC version {version}")
    return data.reshape(cols, rows).T


def _get_textlib() -> Optional[ctypes.CDLL]:
    global _TEXTLIB, _TEXTLIB_FAILED
    if _TEXTLIB is not None or _TEXTLIB_FAILED:
        return _TEXTLIB
    with _LOCK:
        if _TEXTLIB is not None or _TEXTLIB_FAILED:
            return _TEXTLIB
        path = _compile_source(_TEXT_SRC, "smltextproc")
        if path is None:
            _TEXTLIB_FAILED = True
            return None
        lib = ctypes.CDLL(path)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        f32p = ctypes.POINTER(ctypes.c_float)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.sml_murmur3_batch.argtypes = [ctypes.c_char_p, i64p,
                                          ctypes.c_int64, ctypes.c_uint32,
                                          u32p, ctypes.c_int]
        lib.sml_murmur3_batch.restype = None
        lib.sml_vw_count.argtypes = [ctypes.c_char_p, i64p, ctypes.c_int64,
                                     ctypes.c_uint32, i64p, ctypes.c_int]
        lib.sml_vw_count.restype = None
        lib.sml_vw_parse.argtypes = [ctypes.c_char_p, i64p, ctypes.c_int64,
                                     ctypes.c_uint32, ctypes.c_int, i64p,
                                     i32p, i32p, f32p, f32p, f32p, u8p,
                                     ctypes.c_int]
        lib.sml_vw_parse.restype = None
        lib.sml_coo_densify.argtypes = [i32p, i32p, f32p, ctypes.c_int64,
                                        f32p, ctypes.c_int64, ctypes.c_int]
        lib.sml_coo_densify.restype = None
        _TEXTLIB = lib
        return _TEXTLIB


def _concat_utf8(strings) -> Tuple[bytes, np.ndarray]:
    enc = [s.encode("utf-8") if isinstance(s, str) else bytes(s)
           for s in strings]
    offsets = np.zeros(len(enc) + 1, np.int64)
    if enc:
        np.cumsum([len(b) for b in enc], out=offsets[1:])
    return b"".join(enc), offsets


def _p(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def murmur3_batch(strings, seed: int = 0,
                  n_threads: int = 0) -> Optional[np.ndarray]:
    """Hash a batch of strings natively -> uint32 array; None if the
    toolchain is unavailable (callers fall back to the Python hasher)."""
    lib = _get_textlib()
    if lib is None:
        return None
    buf, offsets = _concat_utf8(strings)
    n = len(offsets) - 1
    out = np.empty(n, np.uint32)
    lib.sml_murmur3_batch(buf, _p(offsets, ctypes.c_int64), n,
                          ctypes.c_uint32(seed & 0xFFFFFFFF),
                          _p(out, ctypes.c_uint32), n_threads)
    return out


def vw_parse_batch(lines, num_bits: int, seed: int = 0, n_threads: int = 0):
    """Parse VW-format lines natively.  Returns (rows, idxs, vals, labels,
    weights, has_label) COO arrays, or None without a toolchain."""
    lib = _get_textlib()
    if lib is None:
        return None
    buf, offsets = _concat_utf8(str(l) for l in lines)
    n = len(offsets) - 1
    counts = np.zeros(n, np.int64)
    seed32 = ctypes.c_uint32(seed & 0xFFFFFFFF)
    lib.sml_vw_count(buf, _p(offsets, ctypes.c_int64), n, seed32,
                     _p(counts, ctypes.c_int64), n_threads)
    starts = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    total = int(starts[-1])
    rows = np.empty(total, np.int32)
    idxs = np.empty(total, np.int32)
    vals = np.empty(total, np.float32)
    labels = np.empty(n, np.float32)
    weights = np.empty(n, np.float32)
    has = np.empty(n, np.uint8)
    lib.sml_vw_parse(buf, _p(offsets, ctypes.c_int64), n, seed32,
                     int(num_bits), _p(starts, ctypes.c_int64),
                     _p(rows, ctypes.c_int32), _p(idxs, ctypes.c_int32),
                     _p(vals, ctypes.c_float), _p(labels, ctypes.c_float),
                     _p(weights, ctypes.c_float), _p(has, ctypes.c_uint8),
                     n_threads)
    return rows, idxs, vals, labels, weights, has


def coo_densify(rows: np.ndarray, idxs: np.ndarray, vals: np.ndarray,
                out: np.ndarray) -> bool:
    """out[row, idx] += val natively (rows must be sorted, as the VW
    parser emits them).  Returns False without a toolchain."""
    lib = _get_textlib()
    if lib is None:
        return False
    assert out.dtype == np.float32 and out.flags.c_contiguous
    lib.sml_coo_densify(_p(rows, ctypes.c_int32), _p(idxs, ctypes.c_int32),
                        _p(vals, ctypes.c_float), len(rows),
                        _p(out, ctypes.c_float), out.shape[1], 0)
    return True


__all__ = ["coo_densify", "murmur3_batch", "native_available",
           "read_csv_matrix", "read_colstore", "vw_parse_batch",
           "write_colstore"]
