"""Native runtime bindings.

The reference ships native engines inside jars and extracts them at
runtime (reference: core/env/NativeLoader.java:28-90 — jar → tmpdir →
``System.load``).  The analogue here: the C++ loader compiles ON FIRST
USE with the toolchain baked into the image (``g++ -O3 -shared``) into a
per-user cache directory keyed by source hash, then binds over ctypes —
no wheel step, no pybind11.  Every entry point has a numpy fallback so
the framework degrades gracefully where a toolchain is absent.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "loader.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def _cache_dir() -> str:
    root = os.environ.get("SYNAPSEML_TPU_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "synapseml_tpu", "native")
    os.makedirs(root, exist_ok=True)
    return root


def _build_library() -> Optional[str]:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"libsmlloader_{tag}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
    except (OSError, subprocess.SubprocessError):
        return None
    os.replace(tmp, out)
    return out


def _get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        path = _build_library()
        if path is None:
            _LIB_FAILED = True
            return None
        lib = ctypes.CDLL(path)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.sml_csv_dims.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_char, i64p, i64p]
        lib.sml_csv_dims.restype = ctypes.c_int
        lib.sml_csv_read_f32.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                         ctypes.c_char, ctypes.c_int64,
                                         ctypes.c_int64, f32p, ctypes.c_int]
        lib.sml_csv_read_f32.restype = ctypes.c_int
        lib.sml_colstore_write.argtypes = [ctypes.c_char_p, f32p,
                                           ctypes.c_int64, ctypes.c_int64]
        lib.sml_colstore_write.restype = ctypes.c_int
        lib.sml_colstore_dims.argtypes = [ctypes.c_char_p, i64p, i64p]
        lib.sml_colstore_dims.restype = ctypes.c_int
        lib.sml_colstore_read.argtypes = [ctypes.c_char_p, f32p,
                                          ctypes.c_int64, ctypes.c_int64]
        lib.sml_colstore_read.restype = ctypes.c_int
        _LIB = lib
        return _LIB


def native_available() -> bool:
    return _get_lib() is not None


def _read_header(path: str, delim: str) -> Tuple[bool, list]:
    with open(path, "r", errors="replace") as f:
        first = f.readline().rstrip("\r\n")
    fields = first.split(delim)

    def numeric(s: str) -> bool:
        try:
            float(s)
            return True
        except ValueError:
            return s.strip() == ""

    has_header = not all(numeric(x) for x in fields)
    names = (fields if has_header
             else [f"f{i}" for i in range(len(fields))])
    return has_header, names


def read_csv_matrix(path: str, delim: str = ",",
                    n_threads: int = 0) -> Tuple[np.ndarray, list]:
    """(rows, cols) float32 matrix + column names.  Native path: mmap +
    multithreaded parse; fallback: numpy.genfromtxt."""
    has_header, names = _read_header(path, delim)
    lib = _get_lib()
    if lib is not None:
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        rc = lib.sml_csv_dims(path.encode(), int(has_header),
                              delim.encode(), ctypes.byref(rows),
                              ctypes.byref(cols))
        if rc == 0:
            r, c = rows.value, cols.value
            out = np.empty((c, r), np.float32)  # column-major blocks
            rc = lib.sml_csv_read_f32(
                path.encode(), int(has_header), delim.encode(), r, c,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                int(n_threads))
            if rc >= 0:
                return out.T, names[:c]
    mat = np.genfromtxt(path, delimiter=delim,
                        skip_header=1 if has_header else 0,
                        dtype=np.float32, ndmin=2)
    return mat, names[:mat.shape[1]]


def write_colstore(path: str, matrix: np.ndarray) -> None:
    m = np.ascontiguousarray(np.asarray(matrix, np.float32).T)  # col blocks
    lib = _get_lib()
    if lib is not None:
        rc = lib.sml_colstore_write(
            path.encode(), m.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            matrix.shape[0], matrix.shape[1])
        if rc == 0:
            return
    with open(path, "wb") as f:
        f.write(b"SMLC")
        f.write(np.uint32(1).tobytes())
        f.write(np.int64(matrix.shape[0]).tobytes())
        f.write(np.int64(matrix.shape[1]).tobytes())
        f.write(m.tobytes())


def read_colstore(path: str) -> np.ndarray:
    lib = _get_lib()
    if lib is not None:
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        if lib.sml_colstore_dims(path.encode(), ctypes.byref(rows),
                                 ctypes.byref(cols)) == 0:
            out = np.empty((cols.value, rows.value), np.float32)
            if lib.sml_colstore_read(
                    path.encode(),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    rows.value, cols.value) == 0:
                return out.T
    with open(path, "rb") as f:
        magic = f.read(4)
        if magic != b"SMLC":
            raise IOError(f"{path}: not an SMLC column store")
        np.frombuffer(f.read(4), np.uint32)  # version
        rows = int(np.frombuffer(f.read(8), np.int64)[0])
        cols = int(np.frombuffer(f.read(8), np.int64)[0])
        data = np.frombuffer(f.read(rows * cols * 4), np.float32)
    return data.reshape(cols, rows).T


__all__ = ["native_available", "read_csv_matrix", "read_colstore",
           "write_colstore"]
