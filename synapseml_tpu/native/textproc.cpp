// Native text-processing engine: batch MurmurHash3 and VW-format parsing.
//
// TPU-native counterpart of the reference's C++ text path: Vowpal Wabbit's
// native parser+hasher behind VowpalWabbitNative.learnFromString
// (reference: vw/.../VowpalWabbitBaseLearner.scala:148, the vw-jni C++
// engine) and VowpalWabbitMurmurWithPrefix.scala:80.  Python drives these
// through ctypes with concatenated-buffer + offsets calling conventions
// (no per-string FFI crossings), multithreaded over line ranges.
//
// Semantics mirror synapseml_tpu/models/online/generic.py:parse_vw_line
// exactly — including Python float() strictness (full-token parse or the
// value falls back to 1.0 / the label to "absent").

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <string>
#include <thread>
#include <vector>

namespace {

inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

// MurmurHash3 x86_32 (public domain, Austin Appleby)
uint32_t murmur3_32(const uint8_t* data, size_t len, uint32_t seed) {
  const int nblocks = static_cast<int>(len / 4);
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51, c2 = 0x1b873593;
  for (int i = 0; i < nblocks; i++) {
    uint32_t k1;
    memcpy(&k1, data + 4 * i, 4);
    k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2;
    h1 ^= k1; h1 = rotl32(h1, 13); h1 = h1 * 5 + 0xe6546b64;
  }
  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= tail[2] << 16; [[fallthrough]];
    case 2: k1 ^= tail[1] << 8; [[fallthrough]];
    case 1: k1 ^= tail[0];
            k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2; h1 ^= k1;
  }
  h1 ^= static_cast<uint32_t>(len);
  h1 ^= h1 >> 16; h1 *= 0x85ebca6b; h1 ^= h1 >> 13;
  h1 *= 0xc2b2ae35; h1 ^= h1 >> 16;
  return h1;
}

// Python float(tok) semantics (not raw strtod): no hex literals, single
// underscores allowed strictly between digits, full-token consumption,
// inf/infinity/nan accepted.  (Known residual divergence: non-ASCII
// Unicode digits, which Python accepts — not worth a Unicode tables dep.)
bool parse_full_double(const char* s, size_t n, double* out) {
  if (n == 0) return false;
  std::string norm;
  norm.reserve(n);
  size_t i = 0;
  if (s[0] == '+' || s[0] == '-') { norm.push_back(s[0]); i = 1; }
  // reject hex floats (strtod accepts them, Python float() does not)
  if (i + 1 < n && s[i] == '0' && (s[i + 1] == 'x' || s[i + 1] == 'X'))
    return false;
  for (size_t j = i; j < n; j++) {
    char c = s[j];
    if (c == '_') {
      // Python: a single underscore strictly between two digits
      if (j == 0 || j + 1 >= n ||
          !isdigit(static_cast<unsigned char>(s[j - 1])) ||
          !isdigit(static_cast<unsigned char>(s[j + 1])))
        return false;
      continue;  // strip
    }
    if (c == '(' || c == ')' || isspace(static_cast<unsigned char>(c)))
      return false;  // Python rejects nan(...) forms and inner spaces
    norm.push_back(c);
  }
  if (norm.empty() ||
      (norm.size() == 1 && (norm[0] == '+' || norm[0] == '-')))
    return false;
  char* end = nullptr;
  double v = strtod(norm.c_str(), &end);
  if (end != norm.c_str() + norm.size()) return false;
  *out = v;
  return true;
}

struct Tok { const char* p; size_t n; };

// Python str.split() whitespace: Unicode White_Space plus the 0x1c-0x1f
// separators.  Returns the byte length of the space char at p (0 = not
// whitespace).  Invalid UTF-8 bytes are treated as non-space.
size_t py_space_len(const char* p, const char* e) {
  unsigned char c0 = static_cast<unsigned char>(p[0]);
  if (c0 < 0x80) {
    return ((c0 >= 9 && c0 <= 13) || (c0 >= 28 && c0 <= 31) || c0 == ' ')
        ? 1 : 0;
  }
  if ((c0 == 0xC2) && p + 1 < e) {
    unsigned char c1 = static_cast<unsigned char>(p[1]);
    return (c1 == 0x85 || c1 == 0xA0) ? 2 : 0;   // NEL, NBSP
  }
  if (c0 == 0xE1 && p + 2 < e &&
      static_cast<unsigned char>(p[1]) == 0x9A &&
      static_cast<unsigned char>(p[2]) == 0x80)
    return 3;                                     // U+1680 ogham
  if (c0 == 0xE2 && p + 2 < e) {
    unsigned char c1 = static_cast<unsigned char>(p[1]);
    unsigned char c2 = static_cast<unsigned char>(p[2]);
    if (c1 == 0x80 &&
        ((c2 >= 0x80 && c2 <= 0x8A) ||            // U+2000-200A
         c2 == 0xA8 || c2 == 0xA9 ||              // U+2028/2029
         c2 == 0xAF))                             // U+202F
      return 3;
    if (c1 == 0x81 && c2 == 0x9F) return 3;       // U+205F
  }
  if (c0 == 0xE3 && p + 2 < e &&
      static_cast<unsigned char>(p[1]) == 0x80 &&
      static_cast<unsigned char>(p[2]) == 0x80)
    return 3;                                     // U+3000 ideographic
  return 0;
}

void split_ws(const char* s, const char* e, std::vector<Tok>& out) {
  out.clear();
  const char* p = s;
  while (p < e) {
    size_t sp;
    while (p < e && (sp = py_space_len(p, e)) > 0) p += sp;
    const char* t = p;
    while (p < e && py_space_len(p, e) == 0) p++;
    if (p > t) out.push_back({t, static_cast<size_t>(p - t)});
  }
}

// One parsed feature emit.
struct Emit { uint32_t idx; float val; };

// Parse one VW line; fills feats, label/importance/has_label.
void parse_line(const char* s, const char* e, uint32_t seed, uint32_t dim_mask,
                std::vector<Tok>& scratch, std::string& namebuf,
                std::vector<Emit>& feats, float* label, float* importance,
                uint8_t* has_label) {
  *label = 0.0f; *importance = 1.0f; *has_label = 0;
  const char* bar = static_cast<const char*>(memchr(s, '|', e - s));
  const char* head_end = bar ? bar : e;
  split_ws(s, head_end, scratch);
  if (!scratch.empty()) {
    double v;
    if (parse_full_double(scratch[0].p, scratch[0].n, &v)) {
      *label = static_cast<float>(v);
      *has_label = 1;
      if (scratch.size() > 1 &&
          parse_full_double(scratch[1].p, scratch[1].n, &v)) {
        *importance = static_cast<float>(v);
      }
    }
  }
  if (!bar) return;
  const char* seg = bar + 1;
  while (seg <= e) {
    const char* seg_end =
        static_cast<const char*>(memchr(seg, '|', e - seg));
    if (!seg_end) seg_end = e;
    split_ws(seg, seg_end, scratch);
    size_t first = 0;
    double ns_weight = 1.0;
    const char* ns_p = nullptr;
    size_t ns_n = 0;
    if (!scratch.empty() && seg < seg_end &&
        *seg != ' ' && *seg != '\t') {  // Python: seg[:1] not in (" ", "\t")
      // namespace token attached to the '|'
      const Tok& t = scratch[0];
      const char* colon =
          static_cast<const char*>(memchr(t.p, ':', t.n));
      if (colon) {
        ns_p = t.p; ns_n = colon - t.p;
        double w;
        if (colon + 1 < t.p + t.n &&
            parse_full_double(colon + 1, t.p + t.n - colon - 1, &w)) {
          ns_weight = w;
        }
      } else {
        ns_p = t.p; ns_n = t.n;
      }
      first = 1;
    }
    for (size_t i = first; i < scratch.size(); i++) {
      const Tok& t = scratch[i];
      const char* colon =
          static_cast<const char*>(memchr(t.p, ':', t.n));
      const char* name_p = t.p;
      size_t name_n = colon ? static_cast<size_t>(colon - t.p) : t.n;
      double value = 1.0;
      if (colon && colon + 1 < t.p + t.n) {
        double v;
        if (parse_full_double(colon + 1, t.p + t.n - colon - 1, &v))
          value = v;
      }
      namebuf.assign(ns_p, ns_n);
      namebuf.append(name_p, name_n);
      uint32_t h = murmur3_32(
          reinterpret_cast<const uint8_t*>(namebuf.data()),
          namebuf.size(), seed);
      feats.push_back({h & dim_mask,
                       static_cast<float>(value * ns_weight)});
    }
    if (seg_end == e) break;
    seg = seg_end + 1;
  }
}

void run_threads(int64_t n, int n_threads,
                 const std::function<void(int64_t, int64_t)>& fn) {
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = hc ? static_cast<int>(hc) : 4;
  }
  if (n_threads > n) n_threads = static_cast<int>(n > 0 ? n : 1);
  std::vector<std::thread> ts;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(lo + chunk, n);
    if (lo >= hi) break;
    ts.emplace_back([=, &fn] { fn(lo, hi); });
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

// Batch murmur3: n strings as a concatenated buffer + n+1 offsets.
void sml_murmur3_batch(const char* buf, const int64_t* offsets, int64_t n,
                       uint32_t seed, uint32_t* out, int n_threads) {
  run_threads(n, n_threads, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      out[i] = murmur3_32(
          reinterpret_cast<const uint8_t*>(buf + offsets[i]),
          static_cast<size_t>(offsets[i + 1] - offsets[i]), seed);
    }
  });
}

// Pass 1: per-line feature counts (for exact output allocation).
void sml_vw_count(const char* buf, const int64_t* offsets, int64_t n_lines,
                  uint32_t seed, int64_t* out_counts, int n_threads) {
  run_threads(n_lines, n_threads, [&](int64_t lo, int64_t hi) {
    std::vector<Tok> scratch;
    std::string namebuf;
    std::vector<Emit> feats;
    float lab, imp;
    uint8_t has;
    for (int64_t i = lo; i < hi; i++) {
      feats.clear();
      parse_line(buf + offsets[i], buf + offsets[i + 1], seed, 0xFFFFFFFFu,
                 scratch, namebuf, feats, &lab, &imp, &has);
      out_counts[i] = static_cast<int64_t>(feats.size());
    }
  });
}

// Pass 2: parse + hash, writing each line's features at starts[i].
// out_idx already reduced modulo 2^num_bits via dim_mask.
void sml_vw_parse(const char* buf, const int64_t* offsets, int64_t n_lines,
                  uint32_t seed, int num_bits, const int64_t* starts,
                  int32_t* out_row, int32_t* out_idx, float* out_val,
                  float* out_label, float* out_weight, uint8_t* out_has_label,
                  int n_threads) {
  uint32_t dim_mask = (num_bits >= 32)
      ? 0xFFFFFFFFu : ((1u << num_bits) - 1u);
  run_threads(n_lines, n_threads, [&](int64_t lo, int64_t hi) {
    std::vector<Tok> scratch;
    std::string namebuf;
    std::vector<Emit> feats;
    for (int64_t i = lo; i < hi; i++) {
      feats.clear();
      float lab, imp;
      uint8_t has;
      parse_line(buf + offsets[i], buf + offsets[i + 1], seed, dim_mask,
                 scratch, namebuf, feats, &lab, &imp, &has);
      out_label[i] = has ? lab : 0.0f;
      out_weight[i] = has ? imp : 0.0f;  // unlabeled lines: predict-only
      out_has_label[i] = has;
      int64_t w = starts[i];
      for (const Emit& f : feats) {
        out_row[w] = static_cast<int32_t>(i);
        out_idx[w] = static_cast<int32_t>(f.idx);
        out_val[w] = f.val;
        w++;
      }
    }
  });
}

// COO → dense accumulate: out[row, idx] += val.  Rows arrive sorted (the
// parser writes in line order) so thread ranges split on row boundaries.
void sml_coo_densify(const int32_t* rows, const int32_t* idxs,
                     const float* vals, int64_t nnz, float* out,
                     int64_t dim, int n_threads) {
  run_threads(nnz, n_threads, [&](int64_t lo, int64_t hi) {
    // snap range starts forward to a row boundary to avoid write races
    while (lo > 0 && lo < nnz && rows[lo] == rows[lo - 1]) lo++;
    while (hi < nnz && rows[hi] == rows[hi - 1]) hi++;
    for (int64_t i = lo; i < hi; i++) {
      out[static_cast<int64_t>(rows[i]) * dim + idxs[i]] += vals[i];
    }
  });
}

}  // extern "C"
