// Native columnar loader — the C++ piece of the input pipeline.
//
// The reference's data path into its native engines is C++ behind JNI:
// chunked column stores pushed row-block by row-block into LightGBM
// (reference: lightgbm/.../dataset/DatasetAggregator.scala:117-589 over
// SWIG chunked arrays, StreamingPartitionTask.scala:206-285) with the
// native libs unpacked by NativeLoader (core/env/NativeLoader.java:28).
// Here the native layer owns file parsing: a mmap'd CSV is split at row
// boundaries into per-thread chunks, each thread parses straight into a
// preallocated column-major float32 block (feature-major so device puts
// are contiguous per column), entirely outside the GIL.  A compact
// binary column-store (SMLC) covers the fast re-load path.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <atomic>
#include <cctype>
#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct MappedFile {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;

  bool open(const char* path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size == 0) {
      ::close(fd);
      fd = -1;
      return false;
    }
    size = static_cast<size_t>(st.st_size);
    void* p = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (p == MAP_FAILED) {
      ::close(fd);
      fd = -1;
      return false;
    }
    data = static_cast<const char*>(p);
    return true;
  }

  ~MappedFile() {
    if (data) munmap(const_cast<char*>(data), size);
    if (fd >= 0) ::close(fd);
  }
};

// fast float parse: common fixed/scientific notation, NaN on failure
inline float parse_field(const char* s, const char* end) {
  while (s < end && (*s == ' ' || *s == '\t')) ++s;
  while (end > s && (end[-1] == ' ' || end[-1] == '\t' || end[-1] == '\r'))
    --end;
  if (s == end) return NAN;
  char buf[64];
  size_t len = static_cast<size_t>(end - s);
  if (len >= sizeof(buf)) return NAN;
  memcpy(buf, s, len);
  buf[len] = '\0';
  char* parse_end = nullptr;
  float v = strtof(buf, &parse_end);
  if (parse_end == buf) return NAN;
  return v;
}

inline size_t count_cols(const char* line, const char* end, char delim) {
  size_t n = 1;
  for (const char* p = line; p < end && *p != '\n'; ++p)
    if (*p == delim) ++n;
  return n;
}

const char* line_end(const char* p, const char* end) {
  const char* nl = static_cast<const char*>(
      memchr(p, '\n', static_cast<size_t>(end - p)));
  return nl ? nl : end;
}

}  // namespace

extern "C" {

// Probe dimensions: rows (excluding header when has_header), cols.
// Returns 0 on success.
int sml_csv_dims(const char* path, int has_header, char delim,
                 int64_t* out_rows, int64_t* out_cols) {
  MappedFile f;
  if (!f.open(path)) return -1;
  const char* p = f.data;
  const char* end = f.data + f.size;
  *out_cols = static_cast<int64_t>(count_cols(p, line_end(p, end), delim));
  int64_t lines = 0;
  while (p < end) {
    const char* nl = line_end(p, end);
    if (nl > p) ++lines;  // skip empty lines
    p = nl + 1;
  }
  *out_rows = lines - (has_header ? 1 : 0);
  return *out_rows >= 0 ? 0 : -2;
}

// Parse into column-major out[col * rows + row] (one contiguous block per
// column — the layout Dataset columns want).  Returns 0 on success.
int sml_csv_read_f32(const char* path, int has_header, char delim,
                     int64_t rows, int64_t cols, float* out, int n_threads) {
  MappedFile f;
  if (!f.open(path)) return -1;
  const char* begin = f.data;
  const char* end = f.data + f.size;
  if (has_header) begin = line_end(begin, end) + 1;
  if (begin >= end) return rows == 0 ? 0 : -2;

  if (n_threads <= 0) {
    unsigned hw = std::thread::hardware_concurrency();
    n_threads = hw ? static_cast<int>(hw) : 4;
  }
  if (n_threads > rows && rows > 0) n_threads = static_cast<int>(rows);

  // split [begin, end) into n_threads chunks aligned to line starts, and
  // pre-count rows per chunk so each thread knows its output offset
  std::vector<const char*> starts;
  std::vector<int64_t> row_offsets;
  size_t span = static_cast<size_t>(end - begin);
  starts.push_back(begin);
  for (int t = 1; t < n_threads; ++t) {
    const char* guess = begin + span * static_cast<size_t>(t) /
                                    static_cast<size_t>(n_threads);
    if (guess >= end) break;
    const char* aligned = line_end(guess, end) + 1;
    if (aligned < end && aligned > starts.back()) starts.push_back(aligned);
  }
  starts.push_back(end);
  row_offsets.assign(starts.size(), 0);
  std::vector<std::thread> counters;
  for (size_t t = 0; t + 1 < starts.size(); ++t) {
    counters.emplace_back([&, t] {
      int64_t n = 0;
      for (const char* p = starts[t]; p < starts[t + 1];) {
        const char* nl = line_end(p, starts[t + 1]);
        if (nl > p) ++n;
        p = nl + 1;
      }
      row_offsets[t + 1] = n;
    });
  }
  for (auto& th : counters) th.join();
  int64_t total = 0;
  for (size_t t = 1; t < row_offsets.size(); ++t) {
    int64_t n = row_offsets[t];
    row_offsets[t] = total + n;
    row_offsets[t - 1] = total;
    total += n;
  }
  if (!row_offsets.empty()) row_offsets.back() = total;
  if (total != rows) return -3;

  std::atomic<int> bad_cols{0};
  std::vector<std::thread> workers;
  for (size_t t = 0; t + 1 < starts.size(); ++t) {
    workers.emplace_back([&, t] {
      int64_t row = row_offsets[t];
      for (const char* p = starts[t]; p < starts[t + 1];) {
        const char* nl = line_end(p, starts[t + 1]);
        if (nl > p) {
          const char* field = p;
          int64_t c = 0;
          for (const char* q = p; q <= nl && c < cols; ++q) {
            if (q == nl || *q == delim) {
              out[c * rows + row] = parse_field(field, q);
              field = q + 1;
              ++c;
            }
          }
          if (c != cols) bad_cols.fetch_add(1, std::memory_order_relaxed);
          for (; c < cols; ++c) out[c * rows + row] = NAN;
          ++row;
        }
        p = nl + 1;
      }
    });
  }
  for (auto& th : workers) th.join();
  return bad_cols.load() ? 1 : 0;  // 1 = ragged rows NaN-padded
}

// ---------------------------------------------------------------------------
// SMLC binary column store: magic "SMLC" + u32 version + i64 rows/cols +
// raw little-endian float32 column blocks.
// ---------------------------------------------------------------------------

int sml_colstore_write(const char* path, const float* data, int64_t rows,
                       int64_t cols) {
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  const char magic[4] = {'S', 'M', 'L', 'C'};
  uint32_t version = 1;
  int ok = fwrite(magic, 1, 4, f) == 4 &&
           fwrite(&version, sizeof version, 1, f) == 1 &&
           fwrite(&rows, sizeof rows, 1, f) == 1 &&
           fwrite(&cols, sizeof cols, 1, f) == 1 &&
           fwrite(data, sizeof(float),
                  static_cast<size_t>(rows * cols), f) ==
               static_cast<size_t>(rows * cols);
  fclose(f);
  return ok ? 0 : -2;
}

int sml_colstore_dims(const char* path, int64_t* rows, int64_t* cols) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  char magic[4];
  uint32_t version;
  int ok = fread(magic, 1, 4, f) == 4 && memcmp(magic, "SMLC", 4) == 0 &&
           fread(&version, sizeof version, 1, f) == 1 && version == 1 &&
           fread(rows, sizeof *rows, 1, f) == 1 &&
           fread(cols, sizeof *cols, 1, f) == 1;
  fclose(f);
  return ok ? 0 : -2;
}

int sml_colstore_read(const char* path, float* out, int64_t rows,
                      int64_t cols) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  if (fseek(f, 4 + sizeof(uint32_t) + 2 * sizeof(int64_t), SEEK_SET) != 0) {
    fclose(f);
    return -2;
  }
  size_t want = static_cast<size_t>(rows * cols);
  size_t got = fread(out, sizeof(float), want, f);
  fclose(f);
  return got == want ? 0 : -3;
}

// Quantile binning to uint8: feats row-major (n, f); bounds row-major
// (f, max_bin) with +inf fill past each feature's real boundaries; out
// row-major (n, f).  bin = min(lower_bound(bounds_f, x), max_bin-1) + 1,
// NaN -> 0.  Row-blocked across threads (GIL-free); the uint8 output is
// what rides the host->device link, 4x smaller than raw floats.
int sml_bin_u8(const float* feats, int64_t n, int64_t f,
               const float* bounds, int64_t max_bin, uint8_t* out,
               int n_threads) {
  if (max_bin < 1 || max_bin > 255) return -1;
  if (n_threads <= 0)
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads < 1) n_threads = 1;
  int64_t block = (n + n_threads - 1) / n_threads;
  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * block;
    int64_t hi = std::min(n, lo + block);
    if (lo >= hi) break;
    threads.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i) {
        const float* row = feats + i * f;
        uint8_t* orow = out + i * f;
        for (int64_t j = 0; j < f; ++j) {
          float x = row[j];
          if (std::isnan(x)) {
            orow[j] = 0;
            continue;
          }
          const float* b = bounds + j * max_bin;
          // lower_bound over the (sorted, +inf-padded) boundary row
          int64_t lo_i = 0, len = max_bin;
          while (len > 0) {
            int64_t half = len >> 1;
            if (b[lo_i + half] < x) {
              lo_i += half + 1;
              len -= half + 1;
            } else {
              len = half;
            }
          }
          if (lo_i > max_bin - 1) lo_i = max_bin - 1;
          orow[j] = static_cast<uint8_t>(lo_i + 1);
        }
      }
    });
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"
