"""Cyber-ML: access-anomaly detection via collaborative filtering
(reference: core/src/main/python/synapse/ml/cyber/ — indexers, per-group
scalers, complement-set sampling, and the AccessAnomaly estimator built
on ALS, anomaly/collaborative_filtering.py:1-1229).

TPU re-design: the ALS solves are jit-compiled dense normal-equation
updates (vmapped per-user/per-resource ridge solves on the MXU) instead
of Spark's blocked ALS."""

from .indexers import IdIndexer, IdIndexerModel, MultiIndexer, MultiIndexerModel
from .scalers import (LinearScalarScaler, LinearScalarScalerModel,
                      StandardScalarScaler, StandardScalarScalerModel)
from .complement_access import ComplementAccessTransformer
from .access_anomaly import (AccessAnomaly, AccessAnomalyConfig,
                             AccessAnomalyModel)

__all__ = [
    "IdIndexer", "IdIndexerModel", "MultiIndexer", "MultiIndexerModel",
    "StandardScalarScaler", "StandardScalarScalerModel",
    "LinearScalarScaler", "LinearScalarScalerModel",
    "ComplementAccessTransformer",
    "AccessAnomaly", "AccessAnomalyConfig", "AccessAnomalyModel",
]
