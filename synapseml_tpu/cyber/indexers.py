"""Per-partition id indexers (reference: cyber/feature/indexers.py —
IdIndexer/IdIndexerModel map string ids to contiguous ints per
partition key, with ``undo_transform`` for the reverse mapping)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataset import Dataset
from ..core.params import BoolParam, DictParam, StringParam
from ..core.pipeline import Estimator, Model, Transformer


class IdIndexer(Estimator):
    """Assign 1-based contiguous indices to ids, scoped by partition key
    (reference: indexers.py IdIndexer — ``resetPerPartition`` restarts
    numbering per partition)."""

    inputCol = StringParam(doc="id column to index")
    partitionKey = StringParam(doc="partition/tenant column")
    outputCol = StringParam(doc="index output column")
    resetPerPartition = BoolParam(doc="restart numbering per partition",
                                  default=True)

    def _fit(self, ds: Dataset) -> "IdIndexerModel":
        keys = ds[self.partitionKey]
        vals = ds[self.inputCol]
        mapping: Dict[Any, Dict[Any, int]] = {}
        counter: Dict[Any, int] = {}
        global_count = 0
        for k, v in zip(keys, vals):
            per = mapping.setdefault(k, {})
            if v in per:
                continue
            if self.resetPerPartition:
                counter[k] = counter.get(k, 0) + 1
                per[v] = counter[k]
            else:
                global_count += 1
                per[v] = global_count
        return IdIndexerModel(inputCol=self.inputCol,
                              partitionKey=self.partitionKey,
                              outputCol=self.outputCol,
                              mapping={str(k): {str(v): i
                                                for v, i in per.items()}
                                       for k, per in mapping.items()})


class IdIndexerModel(Model):
    """Apply the learned (partition, id) → index mapping; unseen ids get
    0 (reference uses null; 0 is our sentinel since indices are 1-based)."""

    inputCol = StringParam(doc="id column to index")
    partitionKey = StringParam(doc="partition/tenant column")
    outputCol = StringParam(doc="index output column")
    mapping = DictParam(doc="partition → {id → index}", default=None)

    def _transform(self, ds: Dataset) -> Dataset:
        mapping = self.get("mapping") or {}
        keys = ds[self.partitionKey]
        vals = ds[self.inputCol]
        out = np.zeros(ds.num_rows, dtype=np.int64)
        for i, (k, v) in enumerate(zip(keys, vals)):
            out[i] = mapping.get(str(k), {}).get(str(v), 0)
        return ds.with_column(self.outputCol, out)

    def undo_transform(self, ds: Dataset) -> Dataset:
        """index → original id (reference: IdIndexerModel.undo_transform)."""
        mapping = self.get("mapping") or {}
        inverse = {k: {i: v for v, i in per.items()}
                   for k, per in mapping.items()}
        keys = ds[self.partitionKey]
        idxs = ds[self.outputCol]
        out = np.empty(ds.num_rows, dtype=object)
        for i, (k, ix) in enumerate(zip(keys, idxs)):
            out[i] = inverse.get(str(k), {}).get(int(ix))
        return ds.with_column(self.inputCol, out)


class MultiIndexer(Estimator):
    """Fit several IdIndexers at once (reference: indexers.py
    MultiIndexer)."""

    def __init__(self, indexers: Optional[List[IdIndexer]] = None, **kw):
        super().__init__(**kw)
        self.indexers = list(indexers or [])

    def _fit(self, ds: Dataset) -> "MultiIndexerModel":
        m = MultiIndexerModel()
        m.models = [ix.fit(ds) for ix in self.indexers]
        return m


class MultiIndexerModel(Model):
    models: List[IdIndexerModel]

    def __init__(self, **kw):
        super().__init__(**kw)
        self.models = []

    def _transform(self, ds: Dataset) -> Dataset:
        for m in self.models:
            ds = m.transform(ds)
        return ds

    def get_model_by_input_col(self, col: str) -> Optional[IdIndexerModel]:
        for m in self.models:
            if m.inputCol == col:
                return m
        return None
