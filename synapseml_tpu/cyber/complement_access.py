"""Complement-set sampling (reference: cyber/anomaly/
complement_access.py ComplementAccessTransformer — sample index tuples
from the cartesian range that do NOT appear in the input; used as
negative examples for explicit-feedback CF)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataset import Dataset
from ..core.params import IntParam, ListParam, StringParam
from ..core.pipeline import Transformer


class ComplementAccessTransformer(Transformer):
    """Sample unseen index tuples per partition (reference:
    complement_access.py — factor × |rows| candidates drawn uniformly in
    each indexed column's [min, max], observed tuples removed)."""

    partitionKey = StringParam(doc="partition column (optional)")
    indexedColNamesArr = ListParam(doc="indexed columns to complement")
    complementsetFactor = IntParam(doc="≈ complement rows per input row",
                                   default=2)
    seed = IntParam(doc="sampling seed", default=0)

    def _transform(self, ds: Dataset) -> Dataset:
        cols: List[str] = list(self.indexedColNamesArr or [])
        factor = int(self.complementsetFactor)
        rng = np.random.default_rng(int(self.seed))
        pk = self.get("partitionKey")
        if pk:
            parts: Dict[Any, np.ndarray] = {}
            for i, k in enumerate(ds[pk]):
                parts.setdefault(k, []).append(i)
            groups = {k: np.asarray(v) for k, v in parts.items()}
        else:
            groups = {None: np.arange(ds.num_rows)}

        out_keys: List[Any] = []
        out_cols: Dict[str, List[int]] = {c: [] for c in cols}
        for key, idx in groups.items():
            observed = set(zip(*(ds[c][idx] for c in cols)))
            bounds = [(int(ds[c][idx].min()), int(ds[c][idx].max()))
                      for c in cols]
            n_draw = factor * len(idx)
            draws = np.stack([rng.integers(lo, hi + 1, size=n_draw)
                              for lo, hi in bounds], axis=1)
            seen_draw = set()
            for row in draws:
                tup = tuple(int(v) for v in row)
                if tup in observed or tup in seen_draw:
                    continue
                seen_draw.add(tup)
                out_keys.append(key)
                for c, v in zip(cols, tup):
                    out_cols[c].append(v)

        data: Dict[str, np.ndarray] = {}
        if pk:
            data[pk] = np.asarray(out_keys, dtype=object)
        for c in cols:
            data[c] = np.asarray(out_cols[c], dtype=np.int64)
        return Dataset(data)
