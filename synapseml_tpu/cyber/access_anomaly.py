"""Access-anomaly detection via collaborative filtering (reference:
core/src/main/python/synapse/ml/cyber/anomaly/collaborative_filtering.py
AccessAnomaly/AccessAnomalyModel/AccessAnomalyConfig, :61-1254).

Semantics mirrored from the reference:
- per-tenant CF over (user, resource, likelihood) triples; implicit
  feedback (Hu-Koren confidence weighting) by default, explicit feedback
  with complement-set negatives otherwise;
- output anomaly scores are standardized per tenant so that the training
  access pairs score mean 0 / std 1, with HIGHER = more anomalous
  (reference folds ``-1/std`` and ``-mean`` into bias-extended vectors,
  collaborative_filtering.py:1199-1224 — we keep raw factors and apply
  ``(mean - u·v)/std`` at scoring time, which is the same value);
- pairs listed in the access history score exactly 0.0
  (collaborative_filtering.py:494-509);
- users/resources never seen at fit time score NaN (reference: null);
- user and resource in different connected components of the bipartite
  access graph score +inf (reference: ConnectedComponents,
  collaborative_filtering.py:541-616).

TPU re-design: instead of Spark blocked ALS, each alternating solve is a
batch of dense ridge normal equations — ``vmap``-style einsums build all
per-user (and per-resource) Gram matrices at once and a batched
``jnp.linalg.solve`` factors them, so the whole update runs as a few
large MXU matmuls under one ``jit`` per tenant shape."""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dataset import Dataset
from ..core.params import (BoolParam, DatasetParam, DictParam, FloatParam,
                           IntParam, ListParam, StringParam)
from ..core.pipeline import Estimator, Model


class AccessAnomalyConfig:
    """Default values for AccessAnomaly params (reference:
    collaborative_filtering.py:61-85)."""

    default_tenant_col = "tenant"
    default_user_col = "user"
    default_res_col = "res"
    default_likelihood_col = "likelihood"
    default_output_col = "anomaly_score"

    default_rank = 10
    default_max_iter = 25
    default_reg_param = 1.0
    default_separate_tenants = False

    default_low_value = 5.0
    default_high_value = 10.0

    default_apply_implicit_cf = True
    default_alpha = 1.0

    default_complementset_factor = 2
    default_neg_score = 1.0


@functools.partial(jax.jit, static_argnames=("rank", "max_iter"))
def _als(weights: jnp.ndarray, targets: jnp.ndarray, rank: int,
         max_iter: int, reg: float, key: jnp.ndarray
         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Alternating batched ridge solves for weighted dense CF.

    ``weights`` (nu, nr) are per-entry confidences/weights, ``targets``
    the values being regressed (preferences for implicit CF, scaled
    likelihoods for explicit).  One user-side update builds every
    per-user normal matrix in a single einsum — an (nu, k, k) batch fed
    to a batched Cholesky solve — which is exactly the dense-matmul
    shape the MXU wants; resource side is the transpose."""
    nu, nr = weights.shape
    ku, kv = jax.random.split(key)
    u0 = 0.1 * jax.random.normal(ku, (nu, rank))
    v0 = 0.1 * jax.random.normal(kv, (nr, rank))
    eye = reg * jnp.eye(rank)
    wt = targets * weights

    def solve_side(w, wt_, other):
        # w: (n, m) weights against `other` (m, k) fixed factors
        gram = jnp.einsum("nm,mk,ml->nkl", w, other, other,
                          optimize=True) + eye
        rhs = wt_ @ other                        # (n, k)
        return jnp.linalg.solve(gram, rhs[..., None])[..., 0]

    def body(_, uv):
        u, v = uv
        u = solve_side(weights, wt, v)
        v = solve_side(weights.T, wt.T, u)
        return u, v

    return lax.fori_loop(0, max_iter, body, (u0, v0))


def _connected_components(users: np.ndarray, ress: np.ndarray
                          ) -> Tuple[Dict[Any, int], Dict[Any, int]]:
    """Union-find over the bipartite access graph (reference:
    ConnectedComponents.transform, collaborative_filtering.py:554-616)."""
    parent: Dict[Any, Any] = {}

    def find(x):
        root = x
        while parent.setdefault(root, root) != root:
            root = parent[root]
        while parent[x] != root:     # path compression
            parent[x], x = root, parent[x]
        return root

    for u, r in zip(users, ress):
        parent[find(("u", u))] = find(("r", r))
    comp_ids: Dict[Any, int] = {}
    user_comp: Dict[Any, int] = {}
    res_comp: Dict[Any, int] = {}
    for u in users:
        root = find(("u", u))
        user_comp[u] = comp_ids.setdefault(root, len(comp_ids))
    for r in ress:
        root = find(("r", r))
        res_comp[r] = comp_ids.setdefault(root, len(comp_ids))
    return user_comp, res_comp


class AccessAnomalyModel(Model):
    """Scores (tenant, user, res) rows by standardized CF reconstruction
    (reference: AccessAnomalyModel, collaborative_filtering.py:194-538)."""

    tenantCol = StringParam(doc="tenant column",
                            default=AccessAnomalyConfig.default_tenant_col)
    userCol = StringParam(doc="user column",
                          default=AccessAnomalyConfig.default_user_col)
    resCol = StringParam(doc="resource column",
                         default=AccessAnomalyConfig.default_res_col)
    outputCol = StringParam(doc="anomaly score output column",
                            default=AccessAnomalyConfig.default_output_col)
    userVectors = DictParam(doc="tenant → {user → latent vector}",
                            default=None)
    resVectors = DictParam(doc="tenant → {res → latent vector}",
                           default=None)
    tenantStats = DictParam(doc="tenant → {mean, std} of training dots",
                            default=None)
    userComponents = DictParam(doc="tenant → {user → component id}",
                               default=None)
    resComponents = DictParam(doc="tenant → {res → component id}",
                              default=None)
    historyPairs = ListParam(doc="[tenant, user, res] triples scoring 0",
                             default=None)

    def _transform(self, ds: Dataset) -> Dataset:
        uvecs = self.get("userVectors") or {}
        rvecs = self.get("resVectors") or {}
        stats = self.get("tenantStats") or {}
        ucomp = self.get("userComponents") or {}
        rcomp = self.get("resComponents") or {}
        history = {tuple(t) for t in (self.get("historyPairs") or [])}

        tenants = np.asarray([str(t) for t in ds[self.tenantCol]], object)
        users = np.asarray([str(u) for u in ds[self.userCol]], object)
        ress = np.asarray([str(r) for r in ds[self.resCol]], object)
        out = np.full(ds.num_rows, np.nan, np.float64)

        # batch per tenant: dict lookups once per unique entity, all dot
        # products in one einsum per tenant (scoring is the volume path)
        for t in dict.fromkeys(tenants):
            rows = np.nonzero(tenants == t)[0]
            uv_map, rv_map = uvecs.get(t, {}), rvecs.get(t, {})
            s = stats.get(t, {"mean": 0.0, "std": 1.0})
            std = s["std"] if s["std"] != 0.0 else 1.0

            uniq_u = list(dict.fromkeys(users[rows]))
            uniq_r = list(dict.fromkeys(ress[rows]))
            u_idx = {u: i for i, u in enumerate(uniq_u)}
            r_idx = {r: i for i, r in enumerate(uniq_r)}
            # rank from whichever map is non-empty: a tenant can have an
            # empty user map but rank>1 resource vectors (or vice versa),
            # and a rank-1 matrix would break the assignment below
            if uv_map:
                rank = len(next(iter(uv_map.values())))
            elif rv_map:
                rank = len(next(iter(rv_map.values())))
            else:
                rank = 1
            u_mat = np.zeros((len(uniq_u), rank))
            u_known = np.zeros(len(uniq_u), bool)
            for i, u in enumerate(uniq_u):
                v = uv_map.get(u)
                if v is not None:
                    u_mat[i], u_known[i] = v, True
            r_mat = np.zeros((len(uniq_r), rank))
            r_known = np.zeros(len(uniq_r), bool)
            for i, r in enumerate(uniq_r):
                v = rv_map.get(r)
                if v is not None:
                    r_mat[i], r_known[i] = v, True

            ui = np.array([u_idx[u] for u in users[rows]])
            ri = np.array([r_idx[r] for r in ress[rows]])
            dots = np.einsum("ik,ik->i", u_mat[ui], r_mat[ri])
            scores = (s["mean"] - dots) / std
            scores[~(u_known[ui] & r_known[ri])] = np.nan  # reference: null

            uc, rc = ucomp.get(t, {}), rcomp.get(t, {})
            if uc and rc:
                cu = np.array([uc.get(u, -1) for u in uniq_u])[ui]
                cr = np.array([rc.get(r, -2) for r in uniq_r])[ri]
                cross = (cu >= 0) & (cr >= 0) & (cu != cr)
                scores[cross & (u_known[ui] & r_known[ri])] = np.inf

            if history:
                in_hist = np.array([(t, u, r) in history
                                    for u, r in zip(users[rows], ress[rows])])
                scores[in_hist] = 0.0
            out[rows] = scores
        return ds.with_column(self.outputCol, out)


class AccessAnomaly(Estimator):
    """Per-tenant collaborative-filtering anomaly estimator (reference:
    AccessAnomaly, collaborative_filtering.py:618-1080)."""

    tenantCol = StringParam(doc="tenant/partition column",
                            default=AccessAnomalyConfig.default_tenant_col)
    userCol = StringParam(doc="user column",
                          default=AccessAnomalyConfig.default_user_col)
    resCol = StringParam(doc="resource column",
                         default=AccessAnomalyConfig.default_res_col)
    likelihoodCol = StringParam(
        doc="likelihood-of-access column (e.g. access counts per time "
            "unit)", default=AccessAnomalyConfig.default_likelihood_col)
    outputCol = StringParam(doc="anomaly score output column",
                            default=AccessAnomalyConfig.default_output_col)
    rankParam = IntParam(doc="number of latent factors",
                         default=AccessAnomalyConfig.default_rank)
    maxIter = IntParam(doc="ALS iterations",
                       default=AccessAnomalyConfig.default_max_iter)
    regParam = FloatParam(doc="ridge regularization",
                          default=AccessAnomalyConfig.default_reg_param)
    separateTenants = BoolParam(
        doc="API-parity flag (reference: runs one joint ALS with "
            "cross-tenant-unique indices when False, per-tenant ALS when "
            "True). Our dense per-tenant solves are block-separable-"
            "equivalent to the joint run — tenants never couple in the "
            "objective — so both settings produce the same scores here",
        default=AccessAnomalyConfig.default_separate_tenants)
    lowValue = FloatParam(doc="likelihood rescale range low",
                          default=AccessAnomalyConfig.default_low_value)
    highValue = FloatParam(doc="likelihood rescale range high",
                           default=AccessAnomalyConfig.default_high_value)
    applyImplicitCf = BoolParam(
        doc="implicit-feedback CF (Hu-Koren confidences) vs explicit",
        default=AccessAnomalyConfig.default_apply_implicit_cf)
    alphaParam = FloatParam(doc="implicit-CF confidence scale",
                            default=AccessAnomalyConfig.default_alpha)
    complementsetFactor = IntParam(
        doc="explicit CF: complement negatives per observed row",
        default=AccessAnomalyConfig.default_complementset_factor)
    negScore = FloatParam(
        doc="explicit CF: target value for complement rows",
        default=AccessAnomalyConfig.default_neg_score)
    seed = IntParam(doc="factor init / complement sampling seed", default=0)
    historyAccessDs = DatasetParam(
        doc="optional dataset of known-benign (tenant, user, res) pairs "
            "that must score 0 (reference: historyAccessDf)", default=None)

    def _scale_likelihood(self, vals: np.ndarray) -> np.ndarray:
        """Affine-map this tenant's likelihoods onto [lowValue,
        highValue] (reference: _get_scaled_df via LinearScalarScaler,
        collaborative_filtering.py:843-856)."""
        lo, hi = float(self.lowValue), float(self.highValue)
        vmin, vmax = float(vals.min()), float(vals.max())
        if vmax == vmin:
            return np.full_like(vals, hi)
        return lo + (vals - vmin) * (hi - lo) / (vmax - vmin)

    def _fit(self, ds: Dataset) -> AccessAnomalyModel:
        tenants = ds[self.tenantCol]
        users = ds[self.userCol]
        ress = ds[self.resCol]
        likes = np.asarray(ds[self.likelihoodCol], np.float64)

        rank = int(self.rankParam)
        reg = float(self.regParam)
        alpha = float(self.alphaParam)
        rng = np.random.default_rng(int(self.seed))

        groups: Dict[str, List[int]] = {}
        for i, t in enumerate(tenants):
            groups.setdefault(str(t), []).append(i)

        user_vecs: Dict[str, Dict[str, list]] = {}
        res_vecs: Dict[str, Dict[str, list]] = {}
        tenant_stats: Dict[str, Dict[str, float]] = {}
        user_comp: Dict[str, Dict[str, int]] = {}
        res_comp: Dict[str, Dict[str, int]] = {}

        for t, idx_list in groups.items():
            idx = np.asarray(idx_list)
            t_users = np.asarray([str(u) for u in users[idx]])
            t_ress = np.asarray([str(r) for r in ress[idx]])
            uniq_u = {u: i for i, u in enumerate(dict.fromkeys(t_users))}
            uniq_r = {r: i for i, r in enumerate(dict.fromkeys(t_ress))}
            nu, nr = len(uniq_u), len(uniq_r)
            ui = np.array([uniq_u[u] for u in t_users])
            ri = np.array([uniq_r[r] for r in t_ress])
            scaled = self._scale_likelihood(likes[idx])

            # duplicate (user, res) rows aggregate (every access counts,
            # matching ALS-over-rows semantics); mask from the index pairs
            # so zero/negative scaled likelihoods still count as observed
            dense = np.zeros((nu, nr), np.float32)
            np.add.at(dense, (ui, ri), scaled)
            observed = np.zeros((nu, nr), bool)
            observed[ui, ri] = True
            if bool(self.applyImplicitCf):
                # Hu-Koren: confidence 1 + alpha·r everywhere, binary
                # preference target (reference builds the implicit ALS at
                # collaborative_filtering.py:960-996).
                weights = 1.0 + alpha * dense
                targets = observed.astype(np.float32)
            else:
                # Explicit: regress scaled likelihoods on observed cells
                # plus sampled complement cells pinned to negScore
                # (reference: _enrich_and_normalize + ComplementAccess,
                # collaborative_filtering.py:858-888).
                n_draw = int(self.complementsetFactor) * len(idx)
                cu = rng.integers(0, nu, size=n_draw)
                cr = rng.integers(0, nr, size=n_draw)
                comp = np.zeros_like(observed)
                comp[cu, cr] = True
                comp &= ~observed
                targets = dense.copy()
                targets[comp] = float(self.negScore)
                weights = (observed | comp).astype(np.float32)

            key = jax.random.PRNGKey(int(self.seed))
            u_f, v_f = _als(jnp.asarray(weights), jnp.asarray(targets),
                            rank, int(self.maxIter), reg, key)
            u_np = np.asarray(u_f, np.float64)
            v_np = np.asarray(v_f, np.float64)

            train_dots = np.einsum("ik,ik->i", u_np[ui], v_np[ri])
            std = float(train_dots.std())
            tenant_stats[t] = {"mean": float(train_dots.mean()),
                               "std": std if std != 0.0 else 1.0}
            user_vecs[t] = {u: u_np[i].tolist() for u, i in uniq_u.items()}
            res_vecs[t] = {r: v_np[i].tolist() for r, i in uniq_r.items()}
            uc, rc = _connected_components(t_users, t_ress)
            user_comp[t] = {str(k): v for k, v in uc.items()}
            res_comp[t] = {str(k): v for k, v in rc.items()}

        history = None
        hist_ds = self.get("historyAccessDs")
        if hist_ds is not None:
            history = [[str(t), str(u), str(r)] for t, u, r in
                       zip(hist_ds[self.tenantCol], hist_ds[self.userCol],
                           hist_ds[self.resCol])]

        return AccessAnomalyModel(
            tenantCol=self.tenantCol, userCol=self.userCol,
            resCol=self.resCol, outputCol=self.outputCol,
            userVectors=user_vecs, resVectors=res_vecs,
            tenantStats=tenant_stats, userComponents=user_comp,
            resComponents=res_comp, historyPairs=history)
