"""Per-group scalar scalers (reference: cyber/feature/scalers.py —
StandardScalarScaler standardizes per partition key;
LinearScalarScaler maps each group's [min, max] onto a required
range)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..core.dataset import Dataset
from ..core.params import DictParam, FloatParam, StringParam
from ..core.pipeline import Estimator, Model


def _group_indices(keys: np.ndarray) -> Dict[Any, np.ndarray]:
    out: Dict[Any, list] = {}
    for i, k in enumerate(keys):
        out.setdefault(str(k), []).append(i)
    return {k: np.asarray(v) for k, v in out.items()}


class _PerGroupScalerModel(Model):
    inputCol = StringParam(doc="value column")
    partitionKey = StringParam(doc="group column")
    outputCol = StringParam(doc="scaled output column")
    perGroupStats = DictParam(doc="group → stats", default=None)

    def _norm(self, x: np.ndarray, stats: Dict[str, float]) -> np.ndarray:
        raise NotImplementedError

    def _transform(self, ds: Dataset) -> Dataset:
        stats = self.get("perGroupStats") or {}
        x = np.asarray(ds[self.inputCol], np.float64)
        out = np.empty(ds.num_rows, np.float64)
        for key, idx in _group_indices(ds[self.partitionKey]).items():
            s = stats.get(key)
            if s is None:  # unseen group passes through unscaled
                out[idx] = x[idx]
            else:
                out[idx] = self._norm(x[idx], s)
        return ds.with_column(self.outputCol, out)


class StandardScalarScalerModel(_PerGroupScalerModel):
    """(x - mean)/std per group, times coefficientFactor (reference:
    scalers.py StandardScalarScalerModel)."""

    coefficientFactor = FloatParam(doc="multiplier on the standardized "
                                   "value", default=1.0)

    def _norm(self, x, s):
        std = s["std"] if s["std"] != 0.0 else 1.0
        return float(self.coefficientFactor) * (x - s["mean"]) / std


class StandardScalarScaler(Estimator):
    """Learn per-group mean/std (reference: scalers.py
    StandardScalarScaler)."""

    inputCol = StringParam(doc="value column")
    partitionKey = StringParam(doc="group column")
    outputCol = StringParam(doc="scaled output column")
    coefficientFactor = FloatParam(doc="multiplier", default=1.0)

    def _fit(self, ds: Dataset) -> StandardScalarScalerModel:
        x = np.asarray(ds[self.inputCol], np.float64)
        stats = {}
        for key, idx in _group_indices(ds[self.partitionKey]).items():
            stats[key] = {"mean": float(x[idx].mean()),
                          "std": float(x[idx].std())}
        return StandardScalarScalerModel(
            inputCol=self.inputCol, partitionKey=self.partitionKey,
            outputCol=self.outputCol, perGroupStats=stats,
            coefficientFactor=float(self.coefficientFactor))


class LinearScalarScalerModel(_PerGroupScalerModel):
    """a*x + b per group mapping [min, max] → [minRequired, maxRequired]
    (reference: scalers.py LinearScalarScalerModel — degenerate groups
    map to maxRequired)."""

    def _norm(self, x, s):
        return s["a"] * x + s["b"]


class LinearScalarScaler(Estimator):
    """Learn the per-group affine map (reference: scalers.py
    LinearScalarScaler)."""

    inputCol = StringParam(doc="value column")
    partitionKey = StringParam(doc="group column")
    outputCol = StringParam(doc="scaled output column")
    minRequiredValue = FloatParam(doc="range low", default=0.0)
    maxRequiredValue = FloatParam(doc="range high", default=1.0)

    def _fit(self, ds: Dataset) -> LinearScalarScalerModel:
        x = np.asarray(ds[self.inputCol], np.float64)
        lo, hi = float(self.minRequiredValue), float(self.maxRequiredValue)
        stats = {}
        for key, idx in _group_indices(ds[self.partitionKey]).items():
            xmin, xmax = float(x[idx].min()), float(x[idx].max())
            delta = xmax - xmin
            if delta != 0.0:
                a = (hi - lo) / delta
                b = hi - a * xmax
            else:
                a, b = 0.0, hi
            stats[key] = {"a": a, "b": b}
        return LinearScalarScalerModel(
            inputCol=self.inputCol, partitionKey=self.partitionKey,
            outputCol=self.outputCol, perGroupStats=stats)
