"""Double machine learning (reference: core/.../causal/).

``DoubleMLEstimator`` re-designs causal/DoubleMLEstimator.scala:63 —
per bootstrap iteration, split the data, cross-fit treatment and outcome
nuisance models, and estimate the average treatment effect by regressing
outcome residuals on treatment residuals (Neyman-orthogonal partialling
out); confidence intervals are percentile bootstrap over iterations, as
in the reference's ``maxIter`` loop.

``OrthoForestDMLEstimator`` (causal/OrthoForestDMLEstimator.scala)
estimates *heterogeneous* effects: after residualization it fits a
forest on the Robinson transformation — pseudo-outcome resY/resT with
weights resT² — so each leaf's weighted mean is a local ATE.

``ResidualTransformer`` (causal/ResidualTransformer.scala) emits
observed − predicted.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.dataset import Dataset
from ..core.params import (FloatParam, IntParam, ListParam, PyObjectParam,
                           StringParam)
from ..core.pipeline import Estimator, Model, Transformer


class ResidualTransformer(Transformer):
    """observed - predicted (reference: causal/ResidualTransformer.scala)."""

    observedCol = StringParam(doc="observed value column", default="label")
    predictedCol = StringParam(doc="prediction column", default="prediction")
    outputCol = StringParam(doc="residual column", default="residual")
    classIndex = IntParam(doc="probability-vector index when predictedCol "
                          "holds class probabilities", default=1)

    def _transform(self, ds: Dataset) -> Dataset:
        obs = ds[self.observedCol].astype(np.float64)
        pred_col = ds[self.predictedCol]
        if pred_col.dtype == object:
            idx = int(self.classIndex)
            pred = np.array([np.asarray(v, np.float64).ravel()[idx]
                             for v in pred_col])
        else:
            pred = pred_col.astype(np.float64)
        return ds.with_column(self.outputCol, obs - pred)


def _predictions(model: Model, ds: Dataset, pred_col: str,
                 prob_col: str) -> np.ndarray:
    """Continuous prediction: regression predictionCol, else P(class 1)."""
    out = model.transform(ds)
    if prob_col in out and out[prob_col].dtype == object:
        return np.array([np.asarray(v, np.float64).ravel()[-1]
                         for v in out[prob_col]])
    return out[pred_col].astype(np.float64)


class _DMLParams:
    treatmentModel = PyObjectParam(doc="nuisance estimator for treatment")
    outcomeModel = PyObjectParam(doc="nuisance estimator for outcome")
    treatmentCol = StringParam(doc="treatment column", default="treatment")
    outcomeCol = StringParam(doc="outcome column", default="outcome")
    featuresCol = StringParam(doc="confounder vector column",
                              default="features")
    predictionCol = StringParam(doc="nuisance prediction column",
                                default="prediction")
    probabilityCol = StringParam(doc="nuisance probability column",
                                 default="probability")


class DoubleMLEstimator(_DMLParams, Estimator):
    """Average treatment effect via cross-fitted partialling-out
    (reference: causal/DoubleMLEstimator.scala:63)."""

    maxIter = IntParam(doc="bootstrap iterations", default=1)
    sampleSplitRatio = ListParam(doc="two-fold split weights",
                                 default=[0.5, 0.5])
    confidenceLevel = FloatParam(doc="CI level", default=0.975)
    seed = IntParam(doc="rng seed", default=0)

    def _nuisance_residuals(self, half_fit: Dataset, half_pred: Dataset
                            ) -> Tuple[np.ndarray, np.ndarray]:
        tm: Estimator = self.get("treatmentModel").copy()
        om: Estimator = self.get("outcomeModel").copy()
        for m, col in ((tm, self.treatmentCol), (om, self.outcomeCol)):
            if m.has_param("labelCol"):
                m.set("labelCol", col)
            if m.has_param("featuresCol"):
                m.set("featuresCol", self.featuresCol)
        t_hat = _predictions(tm.fit(half_fit), half_pred,
                             self.predictionCol, self.probabilityCol)
        y_hat = _predictions(om.fit(half_fit), half_pred,
                             self.predictionCol, self.probabilityCol)
        res_t = half_pred[self.treatmentCol].astype(np.float64) - t_hat
        res_y = half_pred[self.outcomeCol].astype(np.float64) - y_hat
        return res_t, res_y

    def _fit(self, ds: Dataset) -> "DoubleMLModel":
        if self.get("treatmentModel") is None or \
                self.get("outcomeModel") is None:
            raise ValueError("treatmentModel and outcomeModel are required")
        rng = np.random.default_rng(int(self.seed))
        ratios = list(self.get_or_default("sampleSplitRatio"))
        effects = []
        for it in range(int(self.maxIter)):
            halves = ds.random_split(ratios, seed=int(rng.integers(1 << 31)))
            a, b = halves[0], halves[1]
            # cross-fitting: fit on A predict B, fit on B predict A
            res_t_b, res_y_b = self._nuisance_residuals(a, b)
            res_t_a, res_y_a = self._nuisance_residuals(b, a)
            res_t = np.concatenate([res_t_a, res_t_b])
            res_y = np.concatenate([res_y_a, res_y_b])
            denom = float((res_t * res_t).sum())
            if denom < 1e-12:
                continue
            effects.append(float((res_t * res_y).sum() / denom))
        if not effects:
            raise ValueError("all DML iterations degenerate (no treatment "
                             "variation after partialling out)")
        model = DoubleMLModel()
        model.set("rawTreatmentEffects", effects)
        model.set("confidenceLevel", float(self.confidenceLevel))
        model._copy_values_from(self)
        return model


class DoubleMLModel(_DMLParams, Model):
    rawTreatmentEffects = PyObjectParam(doc="bootstrap ATE draws")
    confidenceLevel = FloatParam(doc="CI level", default=0.975)

    def get_avg_treatment_effect(self) -> float:
        return float(np.mean(self.get("rawTreatmentEffects")))

    def get_confidence_interval(self) -> Tuple[float, float]:
        draws = np.asarray(self.get("rawTreatmentEffects"), np.float64)
        level = float(self.get_or_default("confidenceLevel"))
        alpha = 1.0 - level
        if len(draws) == 1:
            return (float(draws[0]), float(draws[0]))
        lo, hi = np.quantile(draws, [alpha, level])
        return float(lo), float(hi)

    def get_pvalue(self) -> float:
        """Two-sided p-value for ATE != 0 (normal approx over bootstrap
        draws).  NaN with a single draw — one sample has no spread, so any
        number here would be effect-size independent; raise ``maxIter``."""
        from math import erf, sqrt
        draws = np.asarray(self.get("rawTreatmentEffects"), np.float64)
        if len(draws) < 2:
            return float("nan")
        mu = draws.mean()
        sd = draws.std(ddof=1)
        z = abs(mu) / max(sd, 1e-12)
        return float(2 * (1 - 0.5 * (1 + erf(z / sqrt(2)))))

    def _transform(self, ds: Dataset) -> Dataset:
        ate = self.get_avg_treatment_effect()
        return ds.with_column("treatmentEffect",
                              np.full(ds.num_rows, ate, np.float64))


class OrthoForestDMLEstimator(_DMLParams, Estimator):
    """Heterogeneous treatment effects via residualization + a forest on
    the Robinson transformation (reference:
    causal/OrthoForestDMLEstimator.scala)."""

    heterogeneityModel = PyObjectParam(
        doc="regressor fit on the pseudo-outcome (default: random forest)")
    outputCol = StringParam(doc="per-row effect column",
                            default="treatmentEffect")
    minSampleWeight = FloatParam(doc="clip for resT^2 weights", default=1e-3)
    seed = IntParam(doc="rng seed", default=0)

    def _fit(self, ds: Dataset) -> "OrthoForestDMLModel":
        if self.get("treatmentModel") is None or \
                self.get("outcomeModel") is None:
            raise ValueError("treatmentModel and outcomeModel are required")
        halves = ds.random_split([0.5, 0.5], seed=int(self.seed))
        dml = DoubleMLEstimator()
        dml._paramMap.update({k: v for k, v in self._paramMap.items()
                              if dml.has_param(k)})
        res_t_b, res_y_b = dml._nuisance_residuals(halves[0], halves[1])
        res_t_a, res_y_a = dml._nuisance_residuals(halves[1], halves[0])
        # stitched residual vectors aligned with (B then A) row order
        stitched = halves[1].union(halves[0])
        res_t = np.concatenate([res_t_b, res_t_a])
        res_y = np.concatenate([res_y_b, res_y_a])
        w = np.maximum(res_t * res_t, float(self.minSampleWeight))
        pseudo = res_y / np.copysign(np.maximum(np.abs(res_t), 1e-8), res_t)

        het = self.get("heterogeneityModel")
        if het is None:
            from ..models.gbdt import GBDTRegressor
            het = GBDTRegressor(boostingType="rf", numIterations=32,
                                maxDepth=4)
        het = het.copy()
        if het.has_param("featuresCol"):
            het.set("featuresCol", self.featuresCol)
        if het.has_param("labelCol"):
            het.set("labelCol", "_pseudo_outcome")
        if het.has_param("weightCol"):
            het.set("weightCol", "_robinson_weight")
        train = stitched.with_columns({"_pseudo_outcome": pseudo,
                                       "_robinson_weight": w})
        fitted = het.fit(train)

        model = OrthoForestDMLModel()
        model.set("forestModel", fitted)
        model._copy_values_from(self)
        return model


class OrthoForestDMLModel(_DMLParams, Model):
    forestModel = PyObjectParam(doc="fitted heterogeneity regressor")
    outputCol = StringParam(doc="per-row effect column",
                            default="treatmentEffect")

    def _transform(self, ds: Dataset) -> Dataset:
        inner: Model = self.get("forestModel")
        out = inner.transform(ds)
        pred_col = (inner.predictionCol if inner.has_param("predictionCol")
                    else "prediction")
        return ds.with_column(self.outputCol,
                              out[pred_col].astype(np.float64))
