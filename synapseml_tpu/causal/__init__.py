"""Causal inference (reference: core/.../causal/)."""

from .dml import (DoubleMLEstimator, DoubleMLModel, OrthoForestDMLEstimator,
                  OrthoForestDMLModel, ResidualTransformer)

__all__ = ["DoubleMLEstimator", "DoubleMLModel", "OrthoForestDMLEstimator",
           "OrthoForestDMLModel", "ResidualTransformer"]
