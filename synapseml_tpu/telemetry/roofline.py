"""Roofline auditor: XLA-captured bytes/flops for any jitted step.

ROADMAP item 4's standing requirement is that every perf change lands
with a before/after roofline block in ``BENCH_latest.json``.  This module
is the ONE implementation behind those blocks:

- :func:`capture` — AOT-lower + compile a jitted callable and record
  XLA's own cost analysis (flops, bytes accessed) plus the top
  byte-moving HLOs estimated from the optimized module's result shapes
  (the "where do the bytes go" answer ``cost_analysis`` alone cannot
  give).
- :func:`roofline_block` — turn (bytes/sample, flops/sample, measured
  ms) into the canonical paired-block schema: ``bytes_per_sample`` /
  ``flops_per_sample`` / ``compute_ms`` / ``bandwidth_ms`` /
  ``measured_ms`` / ``frac_of_bandwidth_roofline``, every field numeric
  or null.  Compute/bandwidth bounds come from the per-device-kind spec
  tables below; on a backend with no table entry (e.g. the CPU
  container) they are null — byte reductions are still proven by the
  XLA-captured bytes, but no bandwidth-roofline claim is fabricated
  (the PR-6/PR-8 measurement-honesty pattern).
- :func:`paired_roofline` — the ``{leg}_roofline_before`` /
  ``{leg}_roofline_after`` dict bench.py merges into its record; the
  tier-1 artifact schema check (tests/test_artifacts_json.py) holds any
  record carrying one side of a pair to the full two-sided block.

The chip spec tables live HERE (bench.py imports them) so the auditor,
the StepProfiler gauges and the bench can never disagree on a peak.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: the canonical paired-block field set — schema-checked in tier-1
ROOFLINE_BLOCK_KEYS = (
    "bytes_per_sample", "flops_per_sample", "compute_ms", "bandwidth_ms",
    "measured_ms", "frac_of_bandwidth_roofline",
)

#: peak dense bf16 FLOPs/s by device kind (public spec sheets)
CHIP_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v6 lite": 918e12,   # v6e / Trillium
}

#: HBM bandwidth bytes/s by device kind (public spec sheets)
CHIP_HBM_BW = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,    # v5e
    "TPU v5": 2765e9,        # v5p
    "TPU v6 lite": 1640e9,   # v6e / Trillium
}


def chip_lookup(device, table: Dict[str, float],
                default: Optional[float] = None) -> Optional[float]:
    """Longest-prefix device-kind match into a spec table; ``default``
    (None = "unknown backend, claim nothing") when no entry matches."""
    kind = getattr(device, "device_kind", "") or ""
    best = None
    for name, val in table.items():
        if kind.startswith(name) and (best is None or len(name) > best[0]):
            best = (len(name), val)
    return best[1] if best else default


def chip_peak_flops(device, default: Optional[float] = None):
    return chip_lookup(device, CHIP_PEAK_FLOPS, default)


def chip_hbm_bw(device, default: Optional[float] = None):
    return chip_lookup(device, CHIP_HBM_BW, default)


# ---------------------------------------------------------------------------
# optimized-HLO byte estimation
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1,
                "f8e5m2": 1, "s64": 8, "u64": 8, "s32": 4, "u32": 4,
                "s16": 2, "u16": 2, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
                "pred": 1, "c64": 8, "c128": 16}

_SHAPE_RE = re.compile(
    r"\b(" + "|".join(_DTYPE_BYTES) + r")\[([0-9,]*)\]")
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w\.\-]+)\s*=\s*(.*)$")
_OPCODE_RE = re.compile(r"(?:^|\)|\]|\}|\s)([a-z][a-z0-9\-]*)\(")


def _shape_bytes(segment: str) -> int:
    total = 0
    for m in _SHAPE_RE.finditer(segment):
        n = 1
        dims = m.group(2)
        if dims:
            for d in dims.split(","):
                if d:
                    n *= int(d)
        total += n * _DTYPE_BYTES[m.group(1)]
    return total


def top_byte_hlos(hlo_text: str, k: int = 8) -> List[Dict[str, Any]]:
    """Top byte-moving instructions of an optimized HLO module, estimated
    from RESULT shapes (each instruction's output buffer; operand bytes
    land at their producers, so nothing double-counts).

    Instructions inside fused computations are skipped — a fusion's
    internals never touch HBM, its root materializes once.  Loop bodies
    (while/scan) count ONCE, not per trip, matching how
    ``Compiled.cost_analysis`` itself accounts them — treat both as
    per-dispatch lower bounds under loops.  Returns ``[{"name", "op",
    "mbytes"}, ...]`` largest first."""
    out = []
    in_fused = False
    for raw in hlo_text.splitlines():
        line = raw.strip()
        if line.endswith("{"):
            head = line.split("(", 1)[0]
            in_fused = ("fused_computation" in head or "region_" in head) \
                and "ENTRY" not in line
            continue
        if line == "}":
            in_fused = False
            continue
        if in_fused:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, rest = m.group(1), m.group(2)
        om = _OPCODE_RE.search(rest)
        opcode = om.group(1) if om else "?"
        cut = rest.find("(")
        b = _shape_bytes(rest if cut < 0 else rest[:cut])
        if b:
            out.append({"name": name, "op": opcode, "mbytes": b / 1e6})
    out.sort(key=lambda d: -d["mbytes"])
    return out[:max(1, k)]


# ---------------------------------------------------------------------------
# capture + blocks
# ---------------------------------------------------------------------------

def capture_compiled(compiled, top_k: int = 8) -> Optional[Dict[str, Any]]:
    """Cost entry of an ALREADY-compiled executable: ``{"flops",
    "bytes_accessed", "top_hlos"}`` or None.  The one cost_analysis
    parser — callers that keep their Compiled object to execute it
    (bench legs) share it with :func:`capture` instead of re-deriving
    the dict shape."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        entry: Dict[str, Any] = {
            "flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        }
        try:
            entry["top_hlos"] = top_byte_hlos(compiled.as_text(), k=top_k)
        except Exception:
            entry["top_hlos"] = []
        return entry
    except Exception:
        return None


def capture(fn, *args, top_k: int = 8, **kw) -> Optional[Dict[str, Any]]:
    """AOT-compile ``fn`` on ``args`` and return ``{"flops",
    "bytes_accessed", "top_hlos"}`` (or None — capture must never break
    the caller).  Triggers a fresh compile (``lower().compile()`` does
    not share jit's executable cache): call once per program, off the
    measured window."""
    try:
        return capture_compiled(fn.lower(*args, **kw).compile(),
                                top_k=top_k)
    except Exception:
        return None


def roofline_block(bytes_per_sample: Optional[float],
                   flops_per_sample: Optional[float],
                   measured_ms: Optional[float],
                   device=None,
                   samples: float = 1.0) -> Dict[str, Optional[float]]:
    """The canonical 6-key block for one leg/config.

    ``measured_ms`` is the measured wall time of ``samples`` samples
    (one step, usually); compute/bandwidth bounds are for the same
    ``samples`` against the device's spec-sheet peaks — null on a
    backend with no table entry, so no roofline fraction is invented
    where the bound is unknown."""
    peak = chip_peak_flops(device) if device is not None else None
    bw = chip_hbm_bw(device) if device is not None else None
    compute_ms = (samples * flops_per_sample / peak * 1e3
                  if peak and flops_per_sample else None)
    bandwidth_ms = (samples * bytes_per_sample / bw * 1e3
                    if bw and bytes_per_sample else None)
    frac = (bandwidth_ms / measured_ms
            if bandwidth_ms and measured_ms else None)
    return {
        "bytes_per_sample": bytes_per_sample,
        "flops_per_sample": flops_per_sample,
        "compute_ms": compute_ms,
        "bandwidth_ms": bandwidth_ms,
        "measured_ms": measured_ms,
        "frac_of_bandwidth_roofline": frac,
    }


def check_roofline_block(block: Any) -> None:
    """Schema guard shared with tests/test_artifacts_json.py: a paired
    roofline block is a dict carrying EXACTLY the canonical keys, each
    numeric or null."""
    if not isinstance(block, dict):
        raise ValueError(f"roofline block must be a dict, got "
                         f"{type(block).__name__}")
    missing = [key for key in ROOFLINE_BLOCK_KEYS if key not in block]
    if missing:
        raise ValueError(f"roofline block missing keys {missing}")
    bad = [key for key, v in block.items()
           if v is not None and not isinstance(v, (int, float))]
    if bad:
        raise ValueError(f"roofline block non-numeric fields {bad}")


def paired_roofline(leg: str, before: Dict[str, Optional[float]],
                    after: Dict[str, Optional[float]]) -> Dict[str, Any]:
    """``{leg}_roofline_before`` / ``{leg}_roofline_after`` pair, both
    sides schema-checked before they can enter a bench record."""
    check_roofline_block(before)
    check_roofline_block(after)
    return {f"{leg}_roofline_before": dict(before),
            f"{leg}_roofline_after": dict(after)}


def audit(key: str, fn, *args, samples: float = 1.0,
          measured_ms: Optional[float] = None, device=None,
          **kw) -> Optional[Dict[str, Any]]:
    """One-call wrap of any jitted step: capture its compiled cost and
    produce the per-sample roofline block plus the top byte movers.

    → ``{"key", "bytes_per_sample", "flops_per_sample",
    "arithmetic_intensity", "block", "top_hlos"}`` or None when the
    backend exposes no cost analysis."""
    cost = capture(fn, *args, **kw)
    if cost is None or not cost.get("bytes_accessed"):
        return None
    bps = cost["bytes_accessed"] / max(samples, 1e-9)
    fps = cost["flops"] / max(samples, 1e-9)
    return {
        "key": key,
        "bytes_per_sample": bps,
        "flops_per_sample": fps,
        "arithmetic_intensity": (cost["flops"] / cost["bytes_accessed"]
                                 if cost["bytes_accessed"] else None),
        "block": roofline_block(bps, fps, measured_ms, device=device,
                                samples=samples),
        "top_hlos": cost.get("top_hlos", []),
    }
