"""Unified telemetry: metrics registry, span tracing, exposition, and
atomic bench artifacts.

The observability spine of the TPU-native stack — the analogue (and
superset) of the reference's ``SynapseMLLogging`` structured verb
telemetry plus ``LightGBMPerformance.scala`` phase measures:

- :mod:`.registry` — process-wide ``Counter``/``Gauge``/``Histogram``
  with label sets; thread-safe, resettable (``get_registry()``).
- :mod:`.tracing` — nested host-side spans with Chrome-trace export
  (``span(name, **attrs)``, ``get_tracer()``).
- :mod:`.exposition` — Prometheus text + JSON rendering; served by
  ``ServingServer`` at ``GET /metrics``.
- :mod:`.artifact` — atomic, round-trip-verified JSON artifact writes
  (``write_json``), used by ``bench.py`` so a truncated ``BENCH_*.json``
  cannot recur.
- :mod:`.flight` — the crash flight recorder: a bounded,
  allocation-stable ring of structured events (collectives, checkpoint
  publishes, backoffs, fault firings, heartbeats, rowguard verdicts),
  dumped SIGKILL-atomically for post-mortem bundles.
- :mod:`.roofline` — the roofline auditor: XLA-captured bytes/flops +
  top byte-moving HLOs for any jitted step, and the canonical paired
  before/after roofline block every perf change lands with in
  ``BENCH_latest.json`` (ROADMAP item 4's standing requirement).
- :mod:`.gangplane` — the gang-wide observability plane: cross-rank
  metric/span export over the ``SMLMP_TM:`` wire, ``worker_*{rank=}``
  mirroring into the coordinator's ``/metrics``, multi-lane Chrome-trace
  stitching, schema-checked ``postmortem.json`` bundles, and the
  :class:`~synapseml_tpu.telemetry.gangplane.StepProfiler` train-step
  decomposition (data/compute/collective).

Everything here is stdlib-only and safe to import before jax.

Instrumented layers (all write into the default registry):

====================================  =====================================
``parallel.collectives``              ``collective_calls_total`` /
                                      ``collective_bytes_total`` per op+axis
                                      (trace-time for jitted code),
                                      ``collective_latency_seconds`` for the
                                      host-dispatched allreduce
``models.gbdt`` (booster/trainer)     ``gbdt_phase_seconds`` per phase,
                                      ``gbdt_two_level_active`` gauge,
                                      ``gbdt_iterations_total``
``models.dl.training``                ``dl_train_samples_total`` /
                                      ``dl_train_tokens_total`` counters,
                                      ``dl_train_samples_per_sec`` gauge
``serving`` (server/continuous)       ``serving_records_total``,
                                      ``serving_records_per_sec``,
                                      ``serving_batch_size``,
                                      ``serving_errors_total`` (kinds now
                                      include ``parse`` and ``oom``),
                                      client-side continuous-mode counters
``resilience.rowguard``               ``rowguard_stage_calls_total``,
                                      ``rowguard_rows_total`` per outcome,
                                      ``rowguard_bisection_probes_total``,
                                      ``rowguard_oom_events_total``,
                                      ``rowguard_safe_batch_size`` gauge,
                                      ``quarantine_batches_total`` /
                                      ``quarantine_rows_total``,
                                      ``dataset_all_nan_columns_total``
====================================  =====================================
"""

from .artifact import (SchemaError, check_schema, dumps_checked, read_json,
                       write_json)
from .autotune import (AUTOTUNE_METRICS, Autotuner, CollectiveCostModel,
                       TuneSpace, fit_alpha_beta, register_space,
                       registered_spaces, resolve_entry_point)
from .exposition import (PROMETHEUS_CONTENT_TYPE, render_json,
                         render_prometheus)
from .flight import FlightRecorder, get_flight
from .gangplane import (GangPlane, StepProfiler, TM_MARKER,
                        check_postmortem, parse_telemetry, write_postmortem)
from .registry import (DEFAULT_BUCKETS, SERVING_TOKEN_LATENCY_BUCKETS,
                       SERVING_TTFT_BUCKETS, Counter, Gauge, Histogram,
                       MetricsRegistry, bucket_quantile, get_registry)
from .roofline import (ROOFLINE_BLOCK_KEYS, check_roofline_block,
                       paired_roofline, roofline_block)
from .slo import (SLO_METRICS, SLOZ_SCHEMA, SLOZ_SCHEMA_VERSION, SloStore,
                  SloWindow, WindowedCounter, WindowedHistogram, check_sloz,
                  get_slo_store, plane_tenant, tenant_plane_name)
from .tracing import (RequestTraceStore, Span, Tracer, get_request_tracer,
                      get_tracer, mint_trace_id, span)
from .tunetable import (TUNE_TABLE_ENV, TUNE_TABLE_SCHEMA_VERSION, TunePlane,
                        check_tune_table, check_tunez, device_kind,
                        geometry_key, get_tuneplane, set_tuneplane)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "DEFAULT_BUCKETS", "SERVING_TTFT_BUCKETS",
    "SERVING_TOKEN_LATENCY_BUCKETS", "bucket_quantile",
    "Span", "Tracer", "get_tracer", "span",
    "RequestTraceStore", "get_request_tracer", "mint_trace_id",
    "SloStore", "SloWindow", "WindowedCounter", "WindowedHistogram",
    "check_sloz", "get_slo_store", "SLOZ_SCHEMA", "SLOZ_SCHEMA_VERSION",
    "SLO_METRICS", "plane_tenant", "tenant_plane_name",
    "render_prometheus", "render_json", "PROMETHEUS_CONTENT_TYPE",
    "SchemaError", "check_schema", "dumps_checked", "write_json",
    "read_json",
    "FlightRecorder", "get_flight",
    "GangPlane", "StepProfiler", "TM_MARKER", "check_postmortem",
    "parse_telemetry", "write_postmortem",
    "ROOFLINE_BLOCK_KEYS", "check_roofline_block", "paired_roofline",
    "roofline_block",
    "AUTOTUNE_METRICS", "Autotuner", "CollectiveCostModel", "TuneSpace",
    "fit_alpha_beta", "register_space", "registered_spaces",
    "resolve_entry_point",
    "TUNE_TABLE_ENV", "TUNE_TABLE_SCHEMA_VERSION", "TunePlane",
    "check_tune_table", "check_tunez", "device_kind", "geometry_key",
    "get_tuneplane", "set_tuneplane",
]
