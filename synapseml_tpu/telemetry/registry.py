"""Process-wide metrics registry: Counter / Gauge / Histogram with labels.

The reference ships structured per-verb telemetry (``SynapseMLLogging``)
and per-phase wall measures (``LightGBMPerformance.scala``) but no live,
queryable metric surface; this module is the TPU-native stack's answer —
a single in-process registry every layer (collectives, GBDT phases, DL
steps, serving loops) writes into, exportable as Prometheus text or JSON
(:mod:`synapseml_tpu.telemetry.exposition`).

Design points:

- **stdlib-only** — importable before (or without) jax.
- **thread-safe** — serving loops, the GBDT warm-compile thread, and the
  asyncio listener all write concurrently; every mutation holds the
  metric's lock.
- **resettable** — ``registry.reset()`` zeroes all series (registrations
  survive), so tests can assert deltas without process isolation.
- **get-or-create** — ``registry.counter(name, ...)`` returns the
  existing metric when already registered (same kind + label names), so
  call sites need no import-order coordination.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "DEFAULT_BUCKETS", "SERVING_TTFT_BUCKETS",
           "SERVING_TOKEN_LATENCY_BUCKETS", "bucket_quantile"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Prometheus' default latency buckets (seconds) + +Inf implicit
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: serving-tuned TTFT buckets (seconds): the default ladder starts at
#: 5 ms, which collapses a whole low-latency serving regime into one
#: bucket — these add 1/2.5 ms resolution below it and keep the long
#: tail out to 30 s (queueing under overload).  Shared by the live
#: ``llm_ttft_seconds`` histogram and the SLO window digests, so both
#: surfaces quantize identically.
SERVING_TTFT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0, 30.0)

#: serving-tuned per-token decode-latency buckets (seconds): decode
#: steps on real chips are sub-millisecond, where the Prometheus
#: defaults have zero resolution — the ladder starts at 100 µs.
SERVING_TOKEN_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 1.0)


def bucket_quantile(bounds: Sequence[float], cumulative: Sequence[int],
                    count: int, q: float) -> float:
    """Bucket-interpolated quantile over Prometheus-style CUMULATIVE
    bucket counts (``cumulative[i]`` = observations <= ``bounds[i]``;
    ``count`` includes the implicit +Inf bucket).

    Linear interpolation inside the bucket holding the q-rank, assuming
    a uniform spread (the ``histogram_quantile`` model) and a lower
    edge of 0 for the first bucket — the estimator for non-negative
    observations (latencies).  Ranks landing in the +Inf bucket clamp
    to the highest finite bound.  The estimate is exact at bucket
    boundaries and off by at most one bucket width anywhere else —
    which is why live percentile gauges can ride this instead of
    retaining raw samples.  NaN when the window is empty."""
    if count <= 0:
        return float("nan")
    q = min(1.0, max(0.0, float(q)))
    rank = q * count
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in zip(bounds, cumulative):
        if cum >= rank:
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return float(bound)
            frac = (rank - prev_cum) / in_bucket
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_cum = float(bound), int(cum)
    return float(bounds[-1])


class _Metric:
    """Shared label-series plumbing for the three metric kinds."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames: Tuple[str, ...] = tuple(labelnames)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], object] = {}

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def series(self) -> Dict[Tuple[str, ...], object]:
        """Snapshot of every label-set's current value.  Scalar series
        are immutable floats so a shallow copy IS a snapshot; Histogram
        overrides this to deep-copy its mutable per-series state."""
        with self._lock:
            return dict(self._series)

    def reset(self) -> None:
        with self._lock:
            self._series.clear()

    def remove(self, **labels) -> None:
        """Drop one label-set's series (no-op if absent) — for surfaces
        whose membership shrinks, e.g. a refreshed routing table."""
        key = self._key(labels)
        with self._lock:
            self._series.pop(key, None)


class Counter(_Metric):
    """Monotonically increasing value per label set."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))  # type: ignore[arg-type]


class Gauge(_Metric):
    """Point-in-time value per label set (set/inc/dec)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))  # type: ignore[arg-type]


class Histogram(_Metric):
    """Cumulative-bucket histogram per label set (Prometheus semantics:
    ``bucket[i]`` counts observations <= ``buckets[i]``, +Inf implicit)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Iterable[float]] = None):
        super().__init__(name, help, labelnames)
        bounds = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bounds:
            raise ValueError(f"{self.name}: need at least one bucket bound")
        self.buckets: Tuple[float, ...] = bounds

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        if math.isnan(value):
            return
        key = self._key(labels)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = {"buckets": [0] * len(self.buckets),
                      "sum": 0.0, "count": 0}
                self._series[key] = st
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    st["buckets"][i] += 1            # type: ignore[index]
            st["sum"] += value                       # type: ignore[index]
            st["count"] += 1                         # type: ignore[index]

    def series(self) -> Dict[Tuple[str, ...], object]:
        """Deep-copied snapshot taken under the lock — exposition must
        never see a bucket array mid-observe (a torn read would emit a
        non-monotonic cumulative histogram)."""
        with self._lock:
            return {k: {"buckets": list(v["buckets"]),  # type: ignore[index]
                        "sum": v["sum"], "count": v["count"]}  # type: ignore[index]
                    for k, v in self._series.items()}

    def stats(self, **labels) -> Dict[str, object]:
        key = self._key(labels)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                return {"buckets": [0] * len(self.buckets),
                        "sum": 0.0, "count": 0}
            return {"buckets": list(st["buckets"]),   # type: ignore[index]
                    "sum": st["sum"], "count": st["count"]}  # type: ignore[index]

    def quantile(self, q: float, **labels) -> float:
        """Bucket-interpolated quantile estimate for one label set (see
        :func:`bucket_quantile`): live percentile gauges without raw-
        sample retention, accurate to within one bucket width.  NaN
        when the series has no observations."""
        st = self.stats(**labels)
        return bucket_quantile(self.buckets, st["buckets"],  # type: ignore[arg-type]
                               int(st["count"]), q)  # type: ignore[arg-type]


class MetricsRegistry:
    """Named metric collection with get-or-create registration."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}")
                if existing.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{existing.labelnames}, not {tuple(labelnames)}")
                want = kw.get("buckets")
                if want is not None:
                    want = tuple(sorted(float(b) for b in want))
                    if want != existing.buckets:     # type: ignore[attr-defined]
                        raise ValueError(
                            f"metric {name!r} already registered with "
                            f"buckets {existing.buckets}, "  # type: ignore[attr-defined]
                            f"not {want}")
                return existing
            metric = cls(name, help, labelnames, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def reset(self) -> None:
        """Zero every series; registrations (and cached metric handles
        held by call sites) stay valid."""
        for m in self.metrics():
            m.reset()

    def clear(self) -> None:
        """Drop all registrations — only for tests that exercise
        registration itself; cached handles go stale."""
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> Dict[str, object]:
        """JSON-able view of everything: {name: {kind, help, labelnames,
        series: [{labels, value|stats}]}}."""
        out: Dict[str, object] = {}
        for m in self.metrics():
            series = []
            for key, val in sorted(m.series().items()):
                labels = dict(zip(m.labelnames, key))
                if m.kind == "histogram":
                    series.append({"labels": labels,
                                   "sum": val["sum"],          # type: ignore[index]
                                   "count": val["count"],      # type: ignore[index]
                                   "buckets": dict(zip(
                                       [str(b) for b in m.buckets],  # type: ignore[attr-defined]
                                       val["buckets"]))})      # type: ignore[index]
                else:
                    series.append({"labels": labels, "value": val})
            out[m.name] = {"kind": m.kind, "help": m.help,
                           "labelnames": list(m.labelnames),
                           "series": series}
        return out


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every built-in layer writes to."""
    return _default_registry
