"""Span tracing: nested, queryable, Chrome-trace-exportable.

Horovod's timeline (Sergeev & Del Balso, arXiv:1802.05799) made the
per-op schedule of a distributed run *visible*; the analogue here is a
host-side span tracer: ``with span("gbdt.train", rows=n):`` produces an
in-memory record with parent/child nesting (thread-local stack),
host/process-index attribution, and wall+monotonic timestamps, and the
whole trace exports as Chrome-trace JSON (load in ``chrome://tracing``
or Perfetto).

Device-side op scheduling stays the job of
:func:`synapseml_tpu.core.profiling.trace` (the XLA profiler); spans
cover everything the profiler cannot see — host phases, serving loops,
binning, checkpoint writes — cheaply enough to stay on in production.
"""

from __future__ import annotations

import contextlib
import itertools
import socket
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

__all__ = ["Span", "Tracer", "get_tracer", "span",
           "RequestTraceStore", "get_request_tracer", "mint_trace_id"]

_ids = itertools.count(1)
_tls = threading.local()


def _process_index() -> int:
    """jax.process_index() when jax is up, else 0 — resolved lazily so
    importing telemetry never drags in (or initializes) jax."""
    try:
        import sys
        jax = sys.modules.get("jax")
        if jax is None:
            return 0
        return int(jax.process_index())
    except Exception:
        return 0


@dataclass
class Span:
    """One finished (or live) span."""
    name: str
    span_id: int
    parent_id: Optional[int]
    start_wall_s: float                  # epoch seconds (chrome ts base)
    start_s: float                       # perf_counter
    end_s: Optional[float] = None        # perf_counter; None while live
    attrs: Dict[str, Any] = field(default_factory=dict)
    thread_id: int = 0
    process_index: int = 0
    host: str = ""

    @property
    def duration_s(self) -> float:
        return (self.end_s or time.perf_counter()) - self.start_s


class Tracer:
    """Bounded in-memory trace; one per process is plenty."""

    def __init__(self, max_spans: int = 100_000):
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._dropped = 0
        self._host = socket.gethostname()

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        stack: List[Span] = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        sp = Span(name=name, span_id=next(_ids),
                  parent_id=stack[-1].span_id if stack else None,
                  start_wall_s=time.time(), start_s=time.perf_counter(),
                  attrs=dict(attrs), thread_id=threading.get_ident(),
                  process_index=_process_index(), host=self._host)
        stack.append(sp)
        try:
            yield sp
        finally:
            stack.pop()
            sp.end_s = time.perf_counter()
            with self._lock:
                if len(self._spans) < self.max_spans:
                    self._spans.append(sp)
                else:
                    self._dropped += 1

    def record(self, name: str, duration_s: float, *,
               start_wall_s: Optional[float] = None,
               parent_id: Optional[int] = None, **attrs) -> Span:
        """Append an already-measured interval as a finished span — for
        call sites that keep their own perf_counter bookkeeping (e.g. the
        GBDT ``InstrumentationMeasures``) and publish retrospectively."""
        now_perf = time.perf_counter()
        if start_wall_s is None:
            start_wall_s = time.time() - duration_s
        sp = Span(name=name, span_id=next(_ids), parent_id=parent_id,
                  start_wall_s=start_wall_s,
                  start_s=now_perf - duration_s, end_s=now_perf,
                  attrs=dict(attrs), thread_id=threading.get_ident(),
                  process_index=_process_index(), host=self._host)
        with self._lock:
            if len(self._spans) < self.max_spans:
                self._spans.append(sp)
            else:
                self._dropped += 1
        return sp

    # -- queries -----------------------------------------------------------
    def spans(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def children(self, parent: Span) -> List[Span]:
        return [s for s in self.spans() if s.parent_id == parent.span_id]

    def roots(self) -> List[Span]:
        return [s for s in self.spans() if s.parent_id is None]

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._dropped = 0

    # -- export ------------------------------------------------------------
    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome-trace ("Trace Event Format") dict: complete ("X")
        events, pid = process index, tid = OS thread id, ts/dur in us."""
        events = []
        for s in self.spans():
            if s.end_s is None:
                continue
            events.append({
                "name": s.name, "ph": "X", "cat": "host",
                "ts": s.start_wall_s * 1e6,
                "dur": (s.end_s - s.start_s) * 1e6,
                "pid": s.process_index, "tid": s.thread_id,
                "args": {**s.attrs, "host": s.host,
                         "span_id": s.span_id,
                         "parent_id": s.parent_id},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome(self, path: str) -> Dict[str, Any]:
        """Atomically write the Chrome-trace JSON to ``path`` (via the
        artifact writer, so a crash cannot leave a truncated trace)."""
        from .artifact import write_json
        return write_json(path, self.chrome_trace(),
                          schema=("traceEvents",))


_default_tracer = Tracer()


def get_tracer() -> Tracer:
    return _default_tracer


def span(name: str, **attrs):
    """``with span("phase", key=val):`` on the process-default tracer."""
    return _default_tracer.span(name, **attrs)


# ---------------------------------------------------------------------------
# request-scoped tracing (the serving plane's per-request timelines)
# ---------------------------------------------------------------------------

def mint_trace_id() -> str:
    """A fresh request trace id (opaque hex; minted once per request at
    admission and propagated across serving hops via the
    ``X-SML-Trace-Id`` exchange header)."""
    return uuid.uuid4().hex


class RequestTraceStore:
    """Bounded store of per-request event timelines — the serving
    plane's answer to "follow THIS request from router to retired
    slot" when an aggregate percentile goes bad.

    One *trace* is one request's lifecycle: ``queued`` →
    ``shed``/``admitted`` → ``prefill`` (with its bucket) →
    ``decode``/``verify`` steps (with committed-span sizes) →
    ``retired``/``cancelled``/``expired``.  Producers call
    :meth:`begin` once (None ⇒ this request is not sampled — every
    later call with a None id is a no-op attribute check), then
    :meth:`event` per transition, then :meth:`finish` with the
    outcome.  Finishing also records one ``serving.request`` span on
    the process :class:`Tracer` (so request spans ride the existing
    Chrome-trace/gang-plane export) and one ``request`` event on the
    flight recorder (so a crash bundle names the requests in flight).

    Bounded on BOTH axes: at most ``max_traces`` timelines are
    retained (oldest evicted first) and at most ``max_events`` events
    per timeline (later events are counted, not stored).  Sampling is
    deterministic 1-in-``sample_every`` at :meth:`begin`; a PROPAGATED
    id (minted by an upstream hop) is always sampled, so a
    cross-replica request is never half-traced.  Thread-safe: the
    listener, decode loop, and ``/tracez`` reads interleave freely.
    """

    def __init__(self, max_traces: int = 256, max_events: int = 160,
                 sample_every: int = 1):
        self.max_traces = max(1, int(max_traces))
        self.max_events = max(1, int(max_events))
        self.sample_every = max(0, int(sample_every))
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._seen = 0
        self.sampled = 0
        self.dropped_events = 0

    # -- producing ---------------------------------------------------------
    def begin(self, trace_id: Optional[str] = None,
              **attrs) -> Optional[str]:
        """Start a timeline.  ``trace_id=None`` mints one subject to
        sampling (None returned ⇒ not sampled); a caller-provided id
        (the propagated cross-hop case) is always sampled."""
        with self._lock:
            if trace_id is None:
                self._seen += 1
                if (self.sample_every == 0
                        or (self._seen - 1) % self.sample_every != 0):
                    return None
                trace_id = mint_trace_id()
            self.sampled += 1
            self._traces[trace_id] = {
                "trace_id": trace_id, "started_unix": time.time(),
                "started_s": time.perf_counter(), "attrs": dict(attrs),
                "events": [], "dropped_events": 0,
                "outcome": None, "duration_s": None}
            self._traces.move_to_end(trace_id)
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)
        return trace_id

    def event(self, trace_id: Optional[str], name: str, **attrs) -> None:
        """Append one event (relative-time stamped).  Unknown/None ids
        no-op — the unsampled request's fast path."""
        if trace_id is None:
            return
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                return
            if len(tr["events"]) >= self.max_events:
                tr["dropped_events"] += 1
                self.dropped_events += 1
                return
            tr["events"].append(
                {"t_s": time.perf_counter() - tr["started_s"],
                 "name": name, **attrs})

    def finish(self, trace_id: Optional[str], outcome: str,
               **attrs) -> None:
        """Close a timeline with its terminal outcome (``retired`` /
        ``shed`` / ``cancelled`` / ``expired`` / ``error``) and publish
        the request span + flight event."""
        if trace_id is None:
            return
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None or tr["outcome"] is not None:
                return
            tr["outcome"] = outcome
            tr["duration_s"] = time.perf_counter() - tr["started_s"]
            tr["attrs"].update(attrs)
            started_wall, dur = tr["started_unix"], tr["duration_s"]
            span_attrs = {"trace_id": trace_id, "outcome": outcome,
                          **tr["attrs"]}
        get_tracer().record("serving.request", dur,
                            start_wall_s=started_wall, **span_attrs)
        try:
            from .flight import record as flight_record
            flight_record("request", trace_id=trace_id, outcome=outcome,
                          duration_s=dur)
        except Exception:  # noqa: BLE001 — telemetry must not raise
            pass

    # -- reading -----------------------------------------------------------
    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            tr = self._traces.get(trace_id)
            return None if tr is None else _copy_trace(tr)

    def traces(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-first timelines (live ones included, outcome None);
        ``limit <= 0`` returns none (``[-0:]`` would be the whole
        store — 256 full timelines in one response)."""
        limit = int(limit)
        if limit <= 0:
            return []
        with self._lock:
            out = [_copy_trace(t)
                   for t in list(self._traces.values())[-limit:]]
        out.reverse()
        return out

    def snapshot(self, limit: int = 50) -> Dict[str, Any]:
        """The ``/tracez`` payload: recent timelines + store counters."""
        return {"traces": self.traces(limit), "sampled": self.sampled,
                "sample_every": self.sample_every,
                "dropped_events": self.dropped_events,
                "generated_unix": time.time()}

    def chrome_trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """One request's timeline as Chrome-trace JSON: a single "X"
        span for the whole request plus an instant ("i") event per
        transition — load in chrome://tracing / Perfetto.  Works on a
        LIVE trace too (span runs up to now), so an operator can
        export a request that is stuck mid-decode — which is exactly
        when they want the export."""
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                return None
            base_us = tr["started_unix"] * 1e6
            dur_s = tr["duration_s"]
            if dur_s is None:                     # live: span up to now
                dur_s = time.perf_counter() - tr["started_s"]
            outcome = tr["outcome"]
            attrs = dict(tr["attrs"])
            timeline = [dict(e) for e in tr["events"]]
        events = [{
            "name": "serving.request", "ph": "X", "cat": "request",
            "ts": base_us, "dur": dur_s * 1e6, "pid": 0, "tid": 0,
            "args": {"trace_id": trace_id, "outcome": outcome, **attrs}}]
        for ev in timeline:
            args = {k: v for k, v in ev.items() if k not in ("t_s", "name")}
            events.append({"name": ev["name"], "ph": "i", "cat": "request",
                           "ts": base_us + ev["t_s"] * 1e6, "pid": 0,
                           "tid": 0, "s": "t", "args": args})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()
            self._seen = 0
            self.sampled = 0
            self.dropped_events = 0


def _copy_trace(tr: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(tr)
    out["attrs"] = dict(tr["attrs"])
    out["events"] = [dict(e) for e in tr["events"]]
    out.pop("started_s", None)          # perf_counter base is internal
    return out


_default_request_tracer = RequestTraceStore()


def get_request_tracer() -> RequestTraceStore:
    """The process-wide request-trace store (served at ``/tracez``)."""
    return _default_request_tracer
