"""Span tracing: nested, queryable, Chrome-trace-exportable.

Horovod's timeline (Sergeev & Del Balso, arXiv:1802.05799) made the
per-op schedule of a distributed run *visible*; the analogue here is a
host-side span tracer: ``with span("gbdt.train", rows=n):`` produces an
in-memory record with parent/child nesting (thread-local stack),
host/process-index attribution, and wall+monotonic timestamps, and the
whole trace exports as Chrome-trace JSON (load in ``chrome://tracing``
or Perfetto).

Device-side op scheduling stays the job of
:func:`synapseml_tpu.core.profiling.trace` (the XLA profiler); spans
cover everything the profiler cannot see — host phases, serving loops,
binning, checkpoint writes — cheaply enough to stay on in production.
"""

from __future__ import annotations

import contextlib
import itertools
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

__all__ = ["Span", "Tracer", "get_tracer", "span"]

_ids = itertools.count(1)
_tls = threading.local()


def _process_index() -> int:
    """jax.process_index() when jax is up, else 0 — resolved lazily so
    importing telemetry never drags in (or initializes) jax."""
    try:
        import sys
        jax = sys.modules.get("jax")
        if jax is None:
            return 0
        return int(jax.process_index())
    except Exception:
        return 0


@dataclass
class Span:
    """One finished (or live) span."""
    name: str
    span_id: int
    parent_id: Optional[int]
    start_wall_s: float                  # epoch seconds (chrome ts base)
    start_s: float                       # perf_counter
    end_s: Optional[float] = None        # perf_counter; None while live
    attrs: Dict[str, Any] = field(default_factory=dict)
    thread_id: int = 0
    process_index: int = 0
    host: str = ""

    @property
    def duration_s(self) -> float:
        return (self.end_s or time.perf_counter()) - self.start_s


class Tracer:
    """Bounded in-memory trace; one per process is plenty."""

    def __init__(self, max_spans: int = 100_000):
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._dropped = 0
        self._host = socket.gethostname()

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        stack: List[Span] = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        sp = Span(name=name, span_id=next(_ids),
                  parent_id=stack[-1].span_id if stack else None,
                  start_wall_s=time.time(), start_s=time.perf_counter(),
                  attrs=dict(attrs), thread_id=threading.get_ident(),
                  process_index=_process_index(), host=self._host)
        stack.append(sp)
        try:
            yield sp
        finally:
            stack.pop()
            sp.end_s = time.perf_counter()
            with self._lock:
                if len(self._spans) < self.max_spans:
                    self._spans.append(sp)
                else:
                    self._dropped += 1

    def record(self, name: str, duration_s: float, *,
               start_wall_s: Optional[float] = None,
               parent_id: Optional[int] = None, **attrs) -> Span:
        """Append an already-measured interval as a finished span — for
        call sites that keep their own perf_counter bookkeeping (e.g. the
        GBDT ``InstrumentationMeasures``) and publish retrospectively."""
        now_perf = time.perf_counter()
        if start_wall_s is None:
            start_wall_s = time.time() - duration_s
        sp = Span(name=name, span_id=next(_ids), parent_id=parent_id,
                  start_wall_s=start_wall_s,
                  start_s=now_perf - duration_s, end_s=now_perf,
                  attrs=dict(attrs), thread_id=threading.get_ident(),
                  process_index=_process_index(), host=self._host)
        with self._lock:
            if len(self._spans) < self.max_spans:
                self._spans.append(sp)
            else:
                self._dropped += 1
        return sp

    # -- queries -----------------------------------------------------------
    def spans(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def children(self, parent: Span) -> List[Span]:
        return [s for s in self.spans() if s.parent_id == parent.span_id]

    def roots(self) -> List[Span]:
        return [s for s in self.spans() if s.parent_id is None]

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._dropped = 0

    # -- export ------------------------------------------------------------
    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome-trace ("Trace Event Format") dict: complete ("X")
        events, pid = process index, tid = OS thread id, ts/dur in us."""
        events = []
        for s in self.spans():
            if s.end_s is None:
                continue
            events.append({
                "name": s.name, "ph": "X", "cat": "host",
                "ts": s.start_wall_s * 1e6,
                "dur": (s.end_s - s.start_s) * 1e6,
                "pid": s.process_index, "tid": s.thread_id,
                "args": {**s.attrs, "host": s.host,
                         "span_id": s.span_id,
                         "parent_id": s.parent_id},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome(self, path: str) -> Dict[str, Any]:
        """Atomically write the Chrome-trace JSON to ``path`` (via the
        artifact writer, so a crash cannot leave a truncated trace)."""
        from .artifact import write_json
        return write_json(path, self.chrome_trace(),
                          schema=("traceEvents",))


_default_tracer = Tracer()


def get_tracer() -> Tracer:
    return _default_tracer


def span(name: str, **attrs):
    """``with span("phase", key=val):`` on the process-default tracer."""
    return _default_tracer.span(name, **attrs)
