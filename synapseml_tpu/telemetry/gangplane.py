"""Gang-wide observability plane: cross-rank metric/span export, the
post-mortem bundle writer, and the step-level training profiler.

PR 1's telemetry is strictly per-process; a gang run therefore used to
end with every worker rank's counters, spans and step timings dying with
its process.  This module is the cross-rank layer (the Horovod-timeline
analogue — Sergeev & Del Balso, arXiv:1802.05799 — single-process traces
cannot explain collective stalls):

- **wire export** — each worker periodically serializes a compact metric
  snapshot, its completed spans and the flight-record increment into one
  ``SMLMP_TM:{...}`` line on the result pipe (the ``SMLMP_HB:`` sibling).
  The driver's per-rank readers feed :class:`GangPlane`, which mirrors
  worker metrics into the coordinator's registry under a ``worker_``
  prefix with a ``rank`` label (so the coordinator's ``/metrics`` serves
  the whole gang) and stitches per-rank spans into one multi-lane
  Chrome trace (``pid`` = rank).
- **post-mortem bundles** — :func:`write_postmortem` gathers the failure
  verdict, each rank's flight-record tail (wire tail, or the richer
  on-disk dump a SIGTERMed rank leaves), last durable step and final
  metric snapshot into a schema-checked ``postmortem.json`` via the
  atomic artifact writer.
- **:class:`StepProfiler`** — decomposes each train step's wall time
  into data/compute/collective segments (host-timed; the collective leg
  fed by the dispatch hooks in ``parallel.collectives``), exports
  ``train_step_seconds{model,segment}`` histograms, and optionally
  captures XLA cost analysis (flops, bytes accessed) once per compiled
  fn for a roofline-ready summary — per-rank timing decomposition of
  compute vs. communication, not aggregate throughput alone (Awan et
  al., arXiv:1810.11112).

Stdlib-only; importable before (and without) jax.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from .artifact import SchemaError, write_json
from .flight import get_flight, sanitize_floats as _sanitize
from .registry import MetricsRegistry, get_registry
from .tracing import get_tracer

__all__ = ["TM_MARKER", "TM_INTERVAL_ENV", "OBS_DIR_ENV",
           "TelemetryEmitter", "start_emitter", "parse_telemetry",
           "telemetry_batch", "GangPlane", "mirror_snapshot",
           "StepProfiler", "current_profiler", "observe_collective",
           "check_postmortem", "write_postmortem", "GANG_METRICS"]

#: marker in front of the telemetry-batch JSON line (``SMLMP_HB`` sibling)
TM_MARKER = "SMLMP_TM:"
#: env var the launcher sets to enable wire export (seconds; 0/unset = off)
TM_INTERVAL_ENV = "SMLTPU_TM_INTERVAL_S"
#: env var naming the observability directory (flight dumps, post-mortems)
OBS_DIR_ENV = "SMLTPU_OBS_DIR"

#: newest flight events per wire batch (one batch is one pipe line)
MAX_FLIGHT_PER_BATCH = 200
#: newest spans per wire batch
MAX_SPANS_PER_BATCH = 1000

#: gang-level metric names this plane exports — the hygiene sweep asserts
#: every one of these is documented (worker metrics additionally surface
#: under the ``worker_`` prefix + ``rank`` label, documented as a rule)
GANG_METRICS = frozenset({
    "gangplane_batches_total", "gangplane_spans_total",
    "postmortem_bundles_total", "train_step_seconds", "train_steps_total",
    "serving_replica_probe_status", "train_step_bytes_per_sample",
    "train_step_mfu",
    # live gang shape (registered by parallel.supervisor): the rank
    # count the autoscaler's CapacityArbiter and operators read from
    # /metrics instead of scraping resize_history
    "gang_world_size",
    # serving-plane speculative-decode metrics (registered by
    # models.llm.SlotEngine): mirrored through this plane when serving
    # runs in a gang worker, and held to the same documentation bar by
    # the hygiene sweep
    "llm_spec_accepted_span_size", "llm_spec_draft_hit_total",
    "llm_spec_draft_miss_total",
})


# ---------------------------------------------------------------------------
# worker side: the wire
# ---------------------------------------------------------------------------

def _compact_snapshot(registry: Optional[MetricsRegistry] = None
                      ) -> Dict[str, Any]:
    """Registry snapshot minus help strings (the wire carries values,
    not documentation — help text is re-attached at mirror time)."""
    snap = (registry or get_registry()).snapshot()
    return {name: {"kind": m["kind"], "labelnames": m["labelnames"],
                   "series": m["series"]}
            for name, m in snap.items()}


def _chrome_event(span) -> Dict[str, Any]:
    """One finished Span → a pid-less Chrome complete event (the driver
    assigns ``pid`` = rank when stitching)."""
    return {"name": span.name, "ph": "X", "cat": "host",
            "ts": span.start_wall_s * 1e6,
            "dur": (span.end_s - span.start_s) * 1e6,
            "tid": span.thread_id,
            "args": {**span.attrs, "span_id": span.span_id,
                     "parent_id": span.parent_id}}


def telemetry_batch(rank: int, *, span_cursor: int = 0,
                    flight_seq: int = 0, seq: int = 0,
                    final: bool = False) -> Tuple[Dict[str, Any], int, int]:
    """Build one wire batch → ``(payload, new_span_cursor,
    new_flight_seq)``.  The payload's metric snapshot is cumulative
    (mirrors are SET, not added, so re-sends are idempotent); spans and
    flight events are incremental since the given cursors."""
    tracer = get_tracer()
    spans = tracer.spans()
    if span_cursor > len(spans):        # tracer was reset mid-run
        span_cursor = 0
    new_spans = [s for s in spans[span_cursor:] if s.end_s is not None]
    if len(new_spans) > MAX_SPANS_PER_BATCH:
        new_spans = new_spans[-MAX_SPANS_PER_BATCH:]
    flight = get_flight()
    events = flight.events_since(flight_seq, limit=MAX_FLIGHT_PER_BATCH)
    payload = {
        "rank": int(rank), "seq": int(seq), "ts": time.time(),
        "final": bool(final),
        "metrics": _compact_snapshot(),
        "spans": [_chrome_event(s) for s in new_spans],
        "flight": events,
    }
    new_flight_seq = events[-1]["seq"] if events else flight_seq
    return payload, len(spans), new_flight_seq


def parse_telemetry(line: str) -> Optional[dict]:
    """``SMLMP_TM:{...}`` line → dict (None for other lines or garbage —
    a chatty task must never crash the driver's reader)."""
    if not line.startswith(TM_MARKER):
        return None
    try:
        d = json.loads(line[len(TM_MARKER):])
        return d if isinstance(d, dict) else None
    except ValueError:
        return None


class TelemetryEmitter(threading.Thread):
    """Daemon thread printing one ``SMLMP_TM:`` batch every
    ``interval_s`` — and, via :meth:`emit_now`, a final batch flushed
    synchronously BEFORE the worker's result marker, so a clean exit
    drops no spans or metrics (crashes are covered by the periodic
    batches and the driver-held flight tail)."""

    def __init__(self, rank: int, interval_s: float, stream=None):
        super().__init__(name=f"tm-emitter-r{rank}", daemon=True)
        self.rank = int(rank)
        self.interval_s = float(interval_s)
        self._stream = stream
        self._halt = threading.Event()
        self._emit_lock = threading.Lock()
        self._span_cursor = 0
        self._flight_seq = 0
        self._seq = 0

    def stop(self) -> None:
        self._halt.set()

    def emit_now(self, final: bool = False) -> None:
        """Serialize + write one batch on the caller's thread (the
        emitter lock keeps cursors consistent with the periodic loop)."""
        with self._emit_lock:
            payload, self._span_cursor, self._flight_seq = telemetry_batch(
                self.rank, span_cursor=self._span_cursor,
                flight_seq=self._flight_seq, seq=self._seq, final=final)
            self._seq += 1
            from .artifact import _jsonify
            line = TM_MARKER + json.dumps(payload, default=_jsonify)
            # ONE write call: interleaving with the heartbeat thread's
            # (or the result marker's) writes on shared stdout would
            # corrupt both lines
            stream = self._stream if self._stream is not None else sys.stdout
            stream.write(line + "\n")
            stream.flush()

    def run(self) -> None:
        while not self._halt.is_set():
            try:
                self.emit_now()
            except Exception:
                # a closed pipe at teardown silences this rank's export;
                # the driver already holds everything sent so far
                return
            self._halt.wait(self.interval_s)


def start_emitter(rank: int, interval_s: Optional[float] = None,
                  stream=None) -> Optional[TelemetryEmitter]:
    """Start the wire emitter when export is enabled (``interval_s`` or
    the ``SMLTPU_TM_INTERVAL_S`` env var > 0); returns it, or None."""
    if interval_s is None:
        try:
            interval_s = float(os.environ.get(TM_INTERVAL_ENV, "0") or 0)
        except ValueError:
            interval_s = 0.0
    if interval_s <= 0:
        return None
    emitter = TelemetryEmitter(rank, interval_s, stream=stream)
    emitter.start()
    return emitter


# ---------------------------------------------------------------------------
# driver side: merge + stitch
# ---------------------------------------------------------------------------

def mirror_snapshot(snapshot: Dict[str, Any], *, prefix: str = "worker_",
                    extra_labels: Optional[Dict[str, str]] = None,
                    registry: Optional[MetricsRegistry] = None,
                    help_note: str = "mirrored from a worker rank") -> int:
    """SET a compact snapshot's series into ``registry`` under
    ``prefix<name>`` with ``extra_labels`` appended (labels the source
    already carries are NOT duplicated).  Values are assigned, not
    accumulated, so re-mirroring a cumulative snapshot is idempotent.
    Returns the number of series written; a malformed metric is skipped,
    never raised (exposition must survive a garbled wire line)."""
    reg = registry or get_registry()
    extra = dict(extra_labels or {})
    written = 0
    for name, m in snapshot.items():
        try:
            kind = m.get("kind")
            orig_lns = tuple(m.get("labelnames") or ())
            add = {k: str(v) for k, v in extra.items() if k not in orig_lns}
            lns = orig_lns + tuple(add)
            series = m.get("series") or []
            mname = prefix + name
            if kind == "counter":
                metric = reg.counter(mname, help_note, lns)
            elif kind == "gauge":
                metric = reg.gauge(mname, help_note, lns)
            elif kind == "histogram":
                if not series:
                    continue
                bounds = sorted(float(b) for b in series[0]["buckets"])
                metric = reg.histogram(mname, help_note, lns, buckets=bounds)
            else:
                continue
            for s in series:
                labels = {**(s.get("labels") or {}), **add}
                key = tuple(str(labels.get(ln, "")) for ln in lns)
                if kind == "histogram":
                    by_bound = {float(b): int(n)
                                for b, n in s["buckets"].items()}
                    st = {"buckets": [by_bound.get(b, 0)
                                      for b in metric.buckets],
                          "sum": float(s["sum"]), "count": int(s["count"])}
                    with metric._lock:
                        metric._series[key] = st
                else:
                    with metric._lock:
                        metric._series[key] = float(s["value"])
                written += 1
        except Exception:
            continue
    return written


class _RankState:
    """Driver-held view of one rank's exported telemetry."""

    def __init__(self, span_limit: int, flight_tail: int):
        self.metrics: Optional[Dict[str, Any]] = None
        self.spans: "collections.deque[dict]" = collections.deque(
            maxlen=span_limit)
        self.flight: "collections.deque[dict]" = collections.deque(
            maxlen=flight_tail)
        self.batches = 0
        self.final = False
        self.last_ts: Optional[float] = None


class GangPlane:
    """The coordinator's merged view of every rank's exported telemetry.

    Fed by the launcher's per-rank reader threads (:meth:`ingest`);
    mirrors worker metrics into ``registry`` (default: the process
    registry behind ``/metrics``) as ``worker_<name>{...,rank=<r>}``,
    retains a bounded span store per rank for Chrome-trace stitching,
    and a bounded flight tail per rank for the post-mortem bundle."""

    def __init__(self, n_ranks: int,
                 registry: Optional[MetricsRegistry] = None,
                 span_limit: int = 20_000, flight_tail: int = 256):
        self.n_ranks = int(n_ranks)
        self._registry = registry or get_registry()
        self._lock = threading.Lock()
        self._ranks: Dict[int, _RankState] = {
            r: _RankState(span_limit, flight_tail)
            for r in range(self.n_ranks)}
        self._c_batches = self._registry.counter(
            "gangplane_batches_total",
            "telemetry wire batches ingested from workers", ("rank",))
        self._c_spans = self._registry.counter(
            "gangplane_spans_total",
            "worker spans stitched into the gang trace", ("rank",))

    # -- feeding -----------------------------------------------------------
    def ingest(self, rank: int, payload: Dict[str, Any]) -> None:
        """One parsed ``SMLMP_TM:`` batch.  Thread-safe; never raises
        (a garbled line must not kill the reader thread)."""
        try:
            st = self._ranks.get(int(rank))
            if st is None:
                return
            spans = payload.get("spans") or []
            with self._lock:
                if payload.get("metrics") is not None:
                    st.metrics = payload["metrics"]
                for ev in spans:
                    st.spans.append(dict(ev, pid=int(rank)))
                for ev in payload.get("flight") or []:
                    st.flight.append(ev)
                st.batches += 1
                st.final = st.final or bool(payload.get("final"))
                st.last_ts = payload.get("ts")
            if payload.get("metrics") is not None:
                mirror_snapshot(payload["metrics"],
                                extra_labels={"rank": str(rank)},
                                registry=self._registry)
            self._c_batches.inc(1, rank=str(rank))
            if spans:
                self._c_spans.inc(len(spans), rank=str(rank))
        except Exception:
            pass

    # -- reading -----------------------------------------------------------
    def batches(self, rank: int) -> int:
        with self._lock:
            return self._ranks[rank].batches

    def saw_final(self, rank: int) -> bool:
        with self._lock:
            return self._ranks[rank].final

    def metrics_for(self, rank: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            m = self._ranks[rank].metrics
        return dict(m) if m is not None else None

    def spans_for(self, rank: int) -> List[dict]:
        with self._lock:
            return list(self._ranks[rank].spans)

    def flight_tail(self, rank: int,
                    n: Optional[int] = None) -> List[dict]:
        with self._lock:
            tail = list(self._ranks[rank].flight)
        return tail if n is None else tail[-n:]

    # -- stitching ---------------------------------------------------------
    def chrome_trace(self) -> Dict[str, Any]:
        """All ranks' spans as one Chrome trace: ``pid`` = rank, one
        named lane per rank (process_name metadata events)."""
        events: List[dict] = []
        for r in range(self.n_ranks):
            events.append({"name": "process_name", "ph": "M", "pid": r,
                           "args": {"name": f"rank {r}"}})
            events.extend(self.spans_for(r))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome(self, path: str) -> Dict[str, Any]:
        """Atomically write the stitched multi-lane trace (non-finite
        span attrs stringified — one NaN must not abort the file)."""
        return write_json(path, _sanitize(self.chrome_trace()),
                          schema=("traceEvents",))


# ---------------------------------------------------------------------------
# post-mortem bundles
# ---------------------------------------------------------------------------

def check_postmortem(obj: Any) -> None:
    """Schema validator for ``postmortem.json`` (artifact-writer
    callable form): top-level task/verdict/causes/ranks, every rank
    entry carrying cause, last_step, flight_tail (list) and metrics."""
    if not isinstance(obj, dict):
        raise SchemaError("postmortem bundle must be a JSON object")
    for k in ("task", "verdict", "causes", "ranks", "attempt", "n_ranks",
              "world_size", "created_unix"):
        if k not in obj:
            raise SchemaError(f"postmortem bundle missing key {k!r}")
    if not isinstance(obj["causes"], dict):
        raise SchemaError("causes must be a rank → verdict map")
    if not isinstance(obj["world_size"], int) or obj["world_size"] < 1:
        raise SchemaError("world_size must be a positive rank count")
    rh = obj.get("resize_history", [])
    if not isinstance(rh, list):
        raise SchemaError("resize_history must be a list of resize events")
    for ev in rh:
        if not isinstance(ev, dict) or not {"from", "to",
                                            "direction"} <= set(ev):
            raise SchemaError(
                "resize_history events need from/to/direction keys")
    if not isinstance(obj["ranks"], dict) or not obj["ranks"]:
        raise SchemaError("ranks must be a nonempty rank → state map")
    for r, st in obj["ranks"].items():
        if not isinstance(st, dict):
            raise SchemaError(f"rank {r} entry must be an object")
        for k in ("cause", "last_step", "flight_tail", "metrics"):
            if k not in st:
                raise SchemaError(f"rank {r} entry missing key {k!r}")
        if not isinstance(st["flight_tail"], list):
            raise SchemaError(f"rank {r} flight_tail must be a list")


def _ondisk_flight(obs_dir: str, rank: int) -> Optional[Dict[str, Any]]:
    path = os.path.join(obs_dir, f"flight-rank{rank}.json")
    try:
        with open(path, "r", encoding="utf-8") as f:
            d = json.load(f)
        return d if isinstance(d, dict) else None
    except (OSError, ValueError):
        return None


def write_postmortem(path: str, *, task: str, causes: Dict[int, str],
                     attempt: int, n_ranks: int,
                     plane: Optional[GangPlane] = None,
                     last_steps: Optional[Dict[int, Optional[int]]] = None,
                     obs_dir: Optional[str] = None,
                     tail_events: int = 64,
                     verdict: Optional[str] = None,
                     resize_history: Optional[List[Dict[str, Any]]] = None
                     ) -> Dict[str, Any]:
    """Gather one dead gang attempt into a schema-checked bundle.

    Per rank, the flight tail prefers the on-disk dump a SIGTERMed rank
    left (richer: the whole ring) over the wire tail the driver held —
    unless the wire tail is fresher (higher ``seq``), which is the
    SIGKILL case where the dump never happened.

    ``n_ranks`` is the ATTEMPT's world size (post-resize, not the job's
    launch size) — recorded twice: the legacy ``n_ranks`` key and the
    explicit ``world_size``; ``resize_history`` carries every elastic
    resize the supervisor applied before this attempt died."""
    last_steps = dict(last_steps or {})
    ranks: Dict[str, Any] = {}
    for r in range(int(n_ranks)):
        wire = plane.flight_tail(r) if plane is not None else []
        wire_seq = max((e.get("seq", 0) for e in wire), default=0)
        tail = wire
        if obs_dir:
            dumped = _ondisk_flight(obs_dir, r)
            if dumped is not None and dumped.get("last_seq", 0) >= wire_seq:
                tail = [e for e in dumped.get("events", [])
                        if isinstance(e, dict)]
        ranks[str(r)] = {
            "cause": causes.get(r),
            "last_step": last_steps.get(r),
            "flight_tail": tail[-max(1, tail_events):],
            "metrics": (plane.metrics_for(r) if plane is not None
                        else None),
            "final_batch_seen": (plane.saw_final(r)
                                 if plane is not None else False),
        }
    known_steps = [s for s in last_steps.values() if s is not None]
    bundle = {
        "task": task,
        "verdict": verdict or "; ".join(
            f"rank {r}: {c}" for r, c in sorted(causes.items())) or
        "gang attempt failed (no per-rank verdict)",
        "causes": {str(r): c for r, c in causes.items()},
        "attempt": int(attempt),
        "n_ranks": int(n_ranks),
        "world_size": int(n_ranks),
        "resize_history": list(resize_history or []),
        "last_durable_step": max(known_steps) if known_steps else None,
        "created_unix": time.time(),
        "ranks": ranks,
    }
    out = write_json(path, _sanitize(bundle), schema=check_postmortem)
    get_registry().counter(
        "postmortem_bundles_total",
        "post-mortem bundles written for dead gang attempts",
        ("task",)).inc(1, task=task)
    return out


# ---------------------------------------------------------------------------
# step profiler
# ---------------------------------------------------------------------------

_active = threading.local()

#: train-step buckets: sub-ms dispatches through multi-second steps
_STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0)


def current_profiler() -> Optional["StepProfiler"]:
    """The profiler whose step is open on THIS thread (None outside)."""
    return getattr(_active, "profiler", None)


def observe_collective(seconds: float, nbytes: int = 0,
                       strategy: str = "flat") -> None:
    """Collective-dispatch hook: attributes host-observed collective
    time to the open step's ``collective`` segment, split by the
    planner route that dispatched it (``strategy`` — 'flat' for the
    direct dispatch), so bench pairs isolate routing from codec
    effects.  Called by ``parallel.collectives``; free when no step is
    open."""
    prof = getattr(_active, "profiler", None)
    if prof is not None:
        prof._note_collective(seconds, nbytes, strategy=strategy)


class StepProfiler:
    """Wall-time decomposition of train steps into data / compute /
    collective / other segments.

    Two APIs over the same accounting:

    - context managers (new loops)::

          prof = StepProfiler("dl_text")
          with prof.step(i):
              with prof.segment("data"):    batch = shard(...)
              with prof.segment("compute"): state, m = step_fn(...)

    - begin/mark (retrofits into large existing loops, no re-indent)::

          prof.step_begin(i)
          ...prep...; prof.mark("data")
          ...dispatch...; prof.mark("compute")
          ...eval/checkpoint...; prof.step_end()   # remainder → "other"

    The ``collective`` segment is fed by the dispatch hooks in
    ``parallel.collectives`` (host-dispatched collectives only; in-jit
    collectives execute inside whichever segment dispatched them — the
    hook-fed number is reported alongside, not subtracted).  Per-segment
    wall time lands in ``train_step_seconds{model,segment}`` plus a
    ``total`` series per step; :meth:`summary` returns a roofline-ready
    block, optionally with XLA cost analysis from :meth:`capture_cost`.
    """

    SEGMENTS = ("data", "compute", "collective", "other")

    @staticmethod
    def measure(legs, *, blocks: int = 3, pairs: int = 6,
                timer: Callable[[], float] = time.perf_counter):
        """The bench's alternating min-of-blocks timing protocol as a
        library call (it had grown three hand-rolled copies in bench.py;
        the autotuner is its fourth caller).

        Two shapes of ``legs``:

        - **paired** — a 2-tuple ``(base_fn, other_fn)``: each block
          runs ``pairs`` interleaved executions whose leg order
          alternates pair to pair (cancelling monotone host-load
          drift), takes the per-block MEDIAN of the base times and of
          the other-minus-base differences, and reports the block with
          the minimum difference → ``(base_seconds, delta_seconds)``.
          The median-of-differences statistic is what makes small
          overheads resolvable on a noisy host.
        - **multi** — a dict ``name -> fn``: each block runs every leg
          once, in an order that reverses block to block, and each
          leg's statistic is its MINIMUM across blocks →
          ``{name: seconds}``.  Min-of-blocks is the right statistic
          for "how fast CAN this candidate go" questions (autotuning,
          codec comparisons); contention only ever inflates a block.

        A leg that returns an ``int``/``float`` is trusted as its own
        measurement in seconds (self-timing legs — e.g. a leg that
        reads a profiler's accounting); any other return value means
        the wall clock between ``timer()`` calls is the measurement.
        ``timer`` is injectable so tests can pin the statistics with a
        deterministic clock.
        """

        def _seconds(ret, t0, t1):
            if isinstance(ret, (int, float)) and not isinstance(ret, bool):
                return float(ret)
            return t1 - t0

        blocks = max(1, int(blocks))
        if isinstance(legs, dict):
            names = list(legs)
            best: Dict[str, float] = {}
            for b in range(blocks):
                order = names if b % 2 == 0 else list(reversed(names))
                for name in order:
                    t0 = timer()
                    ret = legs[name]()
                    s = _seconds(ret, t0, timer())
                    prev = best.get(name)
                    best[name] = s if prev is None else min(prev, s)
            return best
        if (isinstance(legs, (tuple, list)) and len(legs) == 2
                and all(callable(f) for f in legs)):
            base_fn, other_fn = legs
            pairs = max(1, int(pairs))
            winner = None
            for _ in range(blocks):
                bases, deltas = [], []
                for i in range(pairs):
                    first, second = ((base_fn, other_fn) if i % 2 == 0
                                     else (other_fn, base_fn))
                    t0 = timer()
                    r1 = first()
                    t1 = timer()
                    r2 = second()
                    t2 = timer()
                    d1 = _seconds(r1, t0, t1)
                    d2 = _seconds(r2, t1, t2)
                    base_s, other_s = (d1, d2) if i % 2 == 0 else (d2, d1)
                    bases.append(base_s)
                    deltas.append(other_s - base_s)
                blk_base = sorted(bases)[len(bases) // 2]
                blk_delta = sorted(deltas)[len(deltas) // 2]
                if winner is None or blk_delta < winner[1]:
                    winner = (blk_base, blk_delta)
            return winner
        raise TypeError("measure() wants a (base_fn, other_fn) pair or a "
                        f"{{name: fn}} dict, got {type(legs).__name__}")

    def __init__(self, model: str,
                 registry: Optional[MetricsRegistry] = None,
                 max_step_records: int = 1024,
                 capture_xla: bool = False):
        reg = registry or get_registry()
        self.model = str(model)
        self.capture_xla = bool(capture_xla)
        self._hist = reg.histogram(
            "train_step_seconds",
            "wall-clock decomposition of train steps, by model and "
            "segment (data/compute/collective/other/total)",
            ("model", "segment"), buckets=_STEP_BUCKETS)
        self._c_steps = reg.counter(
            "train_steps_total", "profiled train steps", ("model",))
        self._lock = threading.Lock()
        self.steps = 0
        self.totals: Dict[str, float] = {s: 0.0 for s in
                                         (*self.SEGMENTS, "total")}
        self.collective_bytes = 0
        #: hook-fed collective seconds by planner route ('flat' = the
        #: direct dispatch) — the strategy split of the collective
        #: segment, so a flat-vs-planned bench pair attributes its
        #: delta to routing rather than codec
        self.collective_by_strategy: Dict[str, float] = {}
        self.costs: Dict[str, Optional[Dict[str, float]]] = {}
        #: per-device items (samples/rows) one step processes, by capture
        #: key — feeds the per-sample gauges in :meth:`summary`
        self._cost_items: Dict[str, float] = {}
        self._g_bytes = reg.gauge(
            "train_step_bytes_per_sample",
            "XLA-captured bytes accessed per sample of the compiled train "
            "step (per device)", ("model", "key"))
        self._g_mfu = reg.gauge(
            "train_step_mfu",
            "achieved model-flops utilization of the profiled train step "
            "against the device's spec-sheet peak (absent table entry = "
            "gauge not set)", ("model", "key"))
        self._tail: "collections.deque[dict]" = collections.deque(
            maxlen=max(1, max_step_records))
        # open-step state (thread-local via _active while a step is open)
        self._open: Optional[dict] = None

    # -- begin/mark API ----------------------------------------------------
    def step_begin(self, index: Optional[int] = None) -> None:
        if self._open is not None:      # a break skipped step_end: close it
            self.step_end()
        now = time.perf_counter()
        self._open = {"index": index, "t0": now, "t_last": now,
                      "segs": {}, "collective": 0.0, "prev": (
                          getattr(_active, "profiler", None))}
        _active.profiler = self

    def mark(self, segment: str) -> None:
        """Attribute the wall time since the previous mark (or step
        begin) to ``segment``."""
        st = self._open
        if st is None:
            return
        now = time.perf_counter()
        st["segs"][segment] = st["segs"].get(segment, 0.0) \
            + (now - st["t_last"])
        st["t_last"] = now

    def step_end(self) -> None:
        st = self._open
        if st is None:
            return
        self._open = None
        _active.profiler = st["prev"]
        now = time.perf_counter()
        total = now - st["t0"]
        segs = st["segs"]
        other = max(0.0, total - sum(segs.values()))
        segs["other"] = segs.get("other", 0.0) + other
        segs["collective"] = segs.get("collective", 0.0) + st["collective"]
        rec = {"step": st["index"], "total": total,
               **{s: segs.get(s, 0.0) for s in self.SEGMENTS}}
        with self._lock:
            self.steps += 1
            self.totals["total"] += total
            for s in self.SEGMENTS:
                self.totals[s] += segs.get(s, 0.0)
            self._tail.append(rec)
        try:
            for s in self.SEGMENTS:
                if segs.get(s, 0.0) > 0.0:
                    self._hist.observe(segs[s], model=self.model, segment=s)
            self._hist.observe(total, model=self.model, segment="total")
            self._c_steps.inc(1, model=self.model)
        except Exception:       # telemetry must never break training
            pass

    def finish(self) -> None:
        """Close any dangling step (early-stopping ``break`` paths)."""
        if self._open is not None:
            self.step_end()

    # -- context API -------------------------------------------------------
    @contextlib.contextmanager
    def step(self, index: Optional[int] = None) -> Iterator[None]:
        self.step_begin(index)
        try:
            yield
        finally:
            self.step_end()

    @contextlib.contextmanager
    def segment(self, name: str) -> Iterator[None]:
        st = self._open
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if st is not None and st is self._open:
                st["segs"][name] = st["segs"].get(name, 0.0) \
                    + (time.perf_counter() - t0)
                st["t_last"] = time.perf_counter()

    # -- collective hook ---------------------------------------------------
    def _note_collective(self, seconds: float, nbytes: int = 0,
                         strategy: str = "flat") -> None:
        st = self._open
        if st is not None:
            st["collective"] += float(seconds)
        with self._lock:
            self.collective_bytes += int(nbytes)
            self.collective_by_strategy[strategy] = \
                self.collective_by_strategy.get(strategy, 0.0) \
                + float(seconds)

    # -- XLA cost analysis -------------------------------------------------
    def capture_cost(self, key: str, fn, *args, items: Optional[float] = None,
                     **kw) -> Optional[Dict[str, float]]:
        """Once per ``key``: lower + compile ``fn`` on ``args`` and
        record XLA's cost analysis (flops, bytes accessed) plus the top
        byte-moving HLOs (via :mod:`telemetry.roofline`).  ``items`` is
        the per-device sample (or row) count one step processes — when
        given, :meth:`summary` also exports the
        ``train_step_bytes_per_sample`` / ``train_step_mfu`` gauges so
        byte regressions surface in live ``/metrics``, not just bench
        runs.  Triggers an AOT compile, so call it at most once per
        compiled fn and only when roofline numbers are wanted
        (``capture_xla=True`` callers); any failure records None and
        never propagates."""
        if key in self.costs:
            return self.costs[key]
        from . import roofline as _roofline
        entry = _roofline.capture(fn, *args, **kw)
        self.costs[key] = entry
        if items:
            self._cost_items[key] = float(items)
        return entry

    # -- export ------------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        """Roofline-ready block: totals, per-step averages, hook-fed
        collective bytes, and achieved flops/s / bytes/s per captured
        compiled fn (against the average compute-segment second)."""
        with self._lock:
            steps = self.steps
            totals = dict(self.totals)
            cbytes = self.collective_bytes
            by_strategy = dict(self.collective_by_strategy)
            tail = list(self._tail)
        avg = {s: (totals[s] / steps if steps else 0.0) for s in totals}
        roofline = {}
        for key, cost in self.costs.items():
            if not cost:
                roofline[key] = None
                continue
            compute_s = avg.get("compute") or avg.get("total") or 0.0
            items = self._cost_items.get(key)
            roofline[key] = {
                **cost,
                "arithmetic_intensity": (
                    cost["flops"] / cost["bytes_accessed"]
                    if cost["bytes_accessed"] else None),
                "achieved_flops_per_sec": (
                    cost["flops"] / compute_s if compute_s else None),
                "achieved_bytes_per_sec": (
                    cost["bytes_accessed"] / compute_s
                    if compute_s else None),
                "bytes_per_sample": (cost["bytes_accessed"] / items
                                     if items else None),
            }
            # live-telemetry export (the bench-independent view of byte
            # regressions); telemetry must never break the summary
            try:
                if items and cost["bytes_accessed"]:
                    self._g_bytes.set(cost["bytes_accessed"] / items,
                                      model=self.model, key=key)
                if compute_s and cost["flops"]:
                    from . import roofline as _roofline
                    import jax as _jax
                    peak = _roofline.chip_peak_flops(_jax.devices()[0])
                    if peak:
                        self._g_mfu.set(
                            cost["flops"] / compute_s / peak,
                            model=self.model, key=key)
            except Exception:
                pass
        return {"model": self.model, "steps": steps, "seconds": totals,
                "per_step_avg_seconds": avg,
                "collective_bytes": cbytes,
                "collective_seconds_by_strategy": by_strategy,
                "roofline": roofline, "last_steps": tail[-16:]}

    def export(self, path: str) -> Dict[str, Any]:
        """Atomically write :meth:`summary` (the reusable form of
        bench.py's hand-rolled round-5 step decomposition)."""
        return write_json(path, _sanitize(self.summary()),
                          schema=("model", "steps", "seconds"))
