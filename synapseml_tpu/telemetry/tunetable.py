"""Persisted per-(device, geometry) tuning tables — the storage half of
the self-tuning performance plane (ROADMAP item 3).

The :class:`~synapseml_tpu.telemetry.autotune.Autotuner` measures real
jitted entry points and records each search space's winner here; every
later construction site (``SlotEngine``, the GBDT trainer,
``CollectiveConfig`` resolution, the collective planner) consults the
SAME loader, so a fleet tunes once and every subsequent process loads
the table — the ``SMLTPU_COMPILE_CACHE_DIR`` pattern, applied to kernel
geometry instead of compiled programs.  ``GangSupervisor`` threads the
directory to workers as :data:`TUNE_TABLE_ENV`.

**The honesty rule** (the roofline-spec-table discipline): an entry
exists only because a real measurement produced it on a matching
``(device_kind, geometry)``.  :meth:`TunePlane.record` refuses
non-positive/non-finite measurements; :meth:`TunePlane.consult` returns
a winner ONLY for an exact ``(space, device_kind, geometry)`` match
that is neither stale nor rejected by the caller's validator — anything
else returns ``None`` and the caller keeps its defaults, dispatching
byte-identically to a table-less process.  Unknown device ⇒ matches no
entry ⇒ defaults.  No number in the table was ever fabricated.

The table file is one schema-versioned JSON document written through
:func:`telemetry.artifact.write_json` (serialize → re-parse →
tmpfile → fsync → rename → dir fsync), so a SIGKILL mid-write leaves
either the old table or the new one, never a torn file.  Every consult
is remembered (outcome + site) and served by ``GET /tunez``.

Stdlib-only at import time; jax is touched lazily (device kind).
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .artifact import SchemaError, read_json, write_json
from .flight import record as flight_record
from .registry import get_registry

__all__ = [
    "TUNE_TABLE_ENV", "TUNE_TABLE_BASENAME", "TUNE_TABLE_SCHEMA_VERSION",
    "TUNE_TABLE_MAX_AGE_ENV", "DEFAULT_MAX_AGE_S",
    "CONSULT_OUTCOMES", "ENTRY_KEYS",
    "device_kind", "geometry_key", "table_path",
    "check_tune_table", "check_tunez",
    "TunePlane", "get_tuneplane", "set_tuneplane",
]

#: env var naming the tuning-table directory — threaded to workers by
#: ``GangSupervisor`` exactly like ``SMLTPU_COMPILE_CACHE_DIR`` (store
#: both in the same place: tables live beside the XLA compile cache)
TUNE_TABLE_ENV = "SMLTPU_TUNE_TABLE_DIR"

#: the single table file inside that directory
TUNE_TABLE_BASENAME = "tunetable.json"

#: bumped on any incompatible entry-shape change; a table written under
#: another version refuses to load WHOLESALE (defaults everywhere) —
#: never a partial reinterpretation of old measurements
TUNE_TABLE_SCHEMA_VERSION = 1

#: entries older than this are ``stale`` (driver rollouts, recabling,
#: firmware — measurements do rot); override via the env var below
DEFAULT_MAX_AGE_S = 30 * 24 * 3600.0
TUNE_TABLE_MAX_AGE_ENV = "SMLTPU_TUNE_TABLE_MAX_AGE_S"

#: required keys of one table entry
ENTRY_KEYS = ("space", "device_kind", "geometry", "winner", "measured_ms",
              "trials", "measured_unix", "source")

#: the closed consult-outcome set (``autotune_table_consults_total``
#: label values; only ``loaded`` changes dispatch)
CONSULT_OUTCOMES = ("loaded", "absent", "mismatch", "stale", "invalid",
                    "disabled")


def device_kind() -> str:
    """This process's accelerator kind as a table key (``'cpu'``,
    ``'tpu_v4'``-style strings, ...), lowercased with spaces collapsed.
    ``'unknown'`` when jax is absent or uninitializable — and an
    unknown device matches no table entry, per the honesty rule."""
    try:
        import jax
        kind = str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"
    kind = "_".join(kind.strip().lower().split())
    return kind or "unknown"


def geometry_key(**dims: Any) -> str:
    """Canonical geometry string: ``k=v`` pairs sorted by key, joined
    with ``,`` — the recorder and every consult site MUST build the key
    through this one function or they silently never match."""
    return ",".join(f"{k}={dims[k]}" for k in sorted(dims))


def table_path(directory: str) -> str:
    return os.path.join(directory, TUNE_TABLE_BASENAME)


def _check_entry(e: Any) -> None:
    if not isinstance(e, dict):
        raise SchemaError(f"tune entry must be an object, got "
                          f"{type(e).__name__}")
    missing = [k for k in ENTRY_KEYS if k not in e]
    if missing:
        raise SchemaError(f"tune entry missing keys {missing}")
    for k in ("space", "device_kind", "geometry", "source"):
        if not isinstance(e[k], str) or not e[k]:
            raise SchemaError(f"tune entry[{k!r}] must be a non-empty "
                              f"string, got {e[k]!r}")
    if not isinstance(e["winner"], dict) or not e["winner"]:
        raise SchemaError("tune entry['winner'] must be a non-empty object")
    ms = e["measured_ms"]
    if (isinstance(ms, bool) or not isinstance(ms, (int, float))
            or not math.isfinite(ms) or ms <= 0.0):
        raise SchemaError(
            f"tune entry['measured_ms'] = {ms!r}: an entry requires a "
            "real, finite, positive measurement (the honesty rule)")
    tr = e["trials"]
    if isinstance(tr, bool) or not isinstance(tr, int) or tr < 1:
        raise SchemaError(f"tune entry['trials'] = {tr!r}: need an int >= 1")
    mu = e["measured_unix"]
    if (isinstance(mu, bool) or not isinstance(mu, (int, float))
            or not math.isfinite(mu)):
        raise SchemaError(f"tune entry['measured_unix'] = {mu!r}")


def check_tune_table(obj: Any) -> None:
    """Callable schema (``telemetry.artifact`` form) for the table file:
    schema-versioned top level + every entry honest."""
    if not isinstance(obj, dict):
        raise SchemaError("tune table must be a JSON object")
    if obj.get("schema_version") != TUNE_TABLE_SCHEMA_VERSION:
        raise SchemaError(
            f"tune table schema_version {obj.get('schema_version')!r} != "
            f"{TUNE_TABLE_SCHEMA_VERSION}: refusing the whole table")
    if not isinstance(obj.get("entries"), list):
        raise SchemaError("tune table needs an 'entries' list")
    for e in obj["entries"]:
        _check_entry(e)


def check_tunez(obj: Any) -> None:
    """Callable schema for the ``GET /tunez`` payload — validated before
    serving (the ``/sloz`` discipline: a malformed snapshot is a 500,
    never a silently wrong 200)."""
    if not isinstance(obj, dict):
        raise SchemaError("/tunez payload must be an object")
    for k in ("schema_version", "directory", "device_kind", "max_age_s",
              "load_error", "entries", "consults"):
        if k not in obj:
            raise SchemaError(f"/tunez payload missing {k!r}")
    if obj["schema_version"] != TUNE_TABLE_SCHEMA_VERSION:
        raise SchemaError(f"/tunez schema_version {obj['schema_version']!r}")
    if not isinstance(obj["entries"], list) \
            or not isinstance(obj["consults"], list):
        raise SchemaError("/tunez entries/consults must be lists")
    for e in obj["entries"]:
        _check_entry(e)
        for k in ("age_s", "stale", "matches_device"):
            if k not in e:
                raise SchemaError(f"/tunez entry missing {k!r}")
    for c in obj["consults"]:
        if not isinstance(c, dict):
            raise SchemaError("/tunez consult must be an object")
        for k in ("site", "space", "geometry", "outcome", "unix"):
            if k not in c:
                raise SchemaError(f"/tunez consult missing {k!r}")
        if c["outcome"] not in CONSULT_OUTCOMES:
            raise SchemaError(f"/tunez consult outcome {c['outcome']!r}")


class TunePlane:
    """The ONE loader between tuning tables and construction sites.

    ``consult(site, space, geometry)`` → the winner config dict, or
    ``None`` (keep defaults).  Every consult lands in
    ``autotune_table_consults_total{space,outcome}``, a flight event,
    and the bounded consult log ``/tunez`` serves — so "which
    construction sites actually loaded the table this process" is an
    introspection answer, not archaeology.
    """

    #: bound on the remembered consult log (/tunez payload size)
    MAX_CONSULTS = 256

    def __init__(self, directory: Optional[str] = None,
                 kind: Optional[str] = None,
                 max_age_s: Optional[float] = None):
        if directory is None:
            directory = os.environ.get(TUNE_TABLE_ENV) or None
        self.directory = str(directory) if directory else None
        if max_age_s is None:
            raw = os.environ.get(TUNE_TABLE_MAX_AGE_ENV, "")
            try:
                max_age_s = float(raw) if raw else DEFAULT_MAX_AGE_S
            except ValueError:
                max_age_s = DEFAULT_MAX_AGE_S
        self.max_age_s = float(max_age_s)
        self._kind = str(kind) if kind else None
        self._lock = threading.RLock()
        self._entries: Dict[Tuple[str, str, str], dict] = {}
        self._loaded = False
        self._load_error: Optional[str] = None
        self._consults: List[dict] = []
        self._c_consults = get_registry().counter(
            "autotune_table_consults_total",
            "tuning-table consults by construction sites, by search space "
            "and outcome (loaded/absent/mismatch/stale/invalid/disabled; "
            "only 'loaded' changes dispatch)", ("space", "outcome"))

    # -- identity ----------------------------------------------------------
    @property
    def kind(self) -> str:
        """Lazy: jax initialization is deferred until the first consult
        or record actually needs the device identity."""
        if self._kind is None:
            self._kind = device_kind()
        return self._kind

    # -- load --------------------------------------------------------------
    def _load_locked(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        if not self.directory:
            return
        path = table_path(self.directory)
        if not os.path.exists(path):
            return
        try:
            obj = read_json(path, schema=check_tune_table)
        except (OSError, ValueError) as e:
            # SchemaError is a ValueError: a version-mismatched or
            # malformed table refuses WHOLESALE — defaults everywhere,
            # never a partial read of measurements we can't interpret
            self._load_error = f"{type(e).__name__}: {e}"
            return
        for e in obj["entries"]:
            self._entries[(e["space"], e["device_kind"], e["geometry"])] = e

    def reload(self) -> None:
        """Drop the in-memory view and re-read the table file (a fleet
        member re-tuned; the planner calls this via ``refresh()``)."""
        with self._lock:
            self._entries.clear()
            self._loaded = False
            self._load_error = None
            self._load_locked()

    # -- consult -----------------------------------------------------------
    def consult(self, site: str, space: str, geometry: str,
                validate: Optional[Callable[[dict], bool]] = None
                ) -> Optional[dict]:
        """→ a copy of the winner config for ``(space, this device,
        geometry)``, or ``None`` = keep defaults.  ``validate`` lets the
        construction site re-check the winner against its OWN gates
        (VMEM fit, divisibility) — a winner failing them is ``invalid``,
        not trusted; a validator that raises counts as rejection."""
        entry: Optional[dict] = None
        with self._lock:
            self._load_locked()
            if not self.directory:
                outcome = "disabled"
            elif self._load_error is not None:
                outcome = "mismatch"
            else:
                e = self._entries.get((str(space), self.kind, str(geometry)))
                if e is None:
                    # measurements exist for this space, but none on THIS
                    # (device, geometry): a mismatch, distinct from a
                    # space nobody ever tuned
                    any_for_space = any(k[0] == space for k in self._entries)
                    outcome = "mismatch" if any_for_space else "absent"
                elif (self.max_age_s > 0 and
                        time.time() - float(e["measured_unix"])
                        > self.max_age_s):
                    outcome = "stale"
                elif validate is not None and not _safe(validate, e["winner"]):
                    outcome = "invalid"
                else:
                    outcome = "loaded"
                    entry = e
            self._consults.append({
                "site": str(site), "space": str(space),
                "geometry": str(geometry), "outcome": outcome,
                "unix": time.time()})
            if len(self._consults) > self.MAX_CONSULTS:
                del self._consults[:-self.MAX_CONSULTS]
        self._c_consults.inc(1, space=str(space), outcome=outcome)
        flight_record("tune_consult", site=str(site), space=str(space),
                      geometry=str(geometry), outcome=outcome)
        return dict(entry["winner"]) if entry is not None else None

    # -- record ------------------------------------------------------------
    def record(self, space: str, geometry: str, winner: Dict[str, Any],
               measured_ms: float, trials: int,
               source: str = "autotune") -> dict:
        """Persist ONE measured winner and atomically rewrite the table.
        The honesty gate lives here: a non-finite or non-positive
        ``measured_ms`` (or an empty winner) raises — a number that was
        never measured cannot enter the table."""
        if not self.directory:
            raise ValueError(
                "TunePlane has no table directory (set SMLTPU_TUNE_TABLE_DIR"
                " or construct with directory=...) — nothing to record into")
        entry = {
            "space": str(space),
            "device_kind": self.kind,
            "geometry": str(geometry),
            "winner": dict(winner),
            "measured_ms": float(measured_ms),
            "trials": int(trials),
            "measured_unix": time.time(),
            "source": str(source),
        }
        _check_entry(entry)    # raises SchemaError on fabricated numbers
        with self._lock:
            self._load_locked()
            self._entries[(entry["space"], entry["device_kind"],
                           entry["geometry"])] = entry
            os.makedirs(self.directory, exist_ok=True)
            obj = {"schema_version": TUNE_TABLE_SCHEMA_VERSION,
                   "written_unix": time.time(),
                   "entries": sorted(
                       self._entries.values(),
                       key=lambda e: (e["space"], e["device_kind"],
                                      e["geometry"]))}
            write_json(table_path(self.directory), obj,
                       schema=check_tune_table)
        flight_record("tune_record", space=entry["space"],
                      device_kind=entry["device_kind"],
                      geometry=entry["geometry"],
                      measured_ms=entry["measured_ms"],
                      trials=entry["trials"], source=entry["source"])
        return entry

    # -- introspection -----------------------------------------------------
    def snapshot(self) -> dict:
        """The ``GET /tunez`` payload: every loaded entry with staleness
        and device-match annotations, plus the consult log."""
        with self._lock:
            self._load_locked()
            now = time.time()
            entries = []
            for e in sorted(self._entries.values(),
                            key=lambda e: (e["space"], e["device_kind"],
                                           e["geometry"])):
                age = now - float(e["measured_unix"])
                entries.append({
                    **e,
                    "age_s": age,
                    "stale": bool(self.max_age_s > 0
                                  and age > self.max_age_s),
                    "matches_device": e["device_kind"] == self.kind,
                })
            return {
                "schema_version": TUNE_TABLE_SCHEMA_VERSION,
                "directory": self.directory,
                "device_kind": self.kind,
                "max_age_s": self.max_age_s,
                "load_error": self._load_error,
                "entries": entries,
                "consults": list(self._consults),
            }


def _safe(validate: Callable[[dict], bool], winner: dict) -> bool:
    try:
        return bool(validate(dict(winner)))
    except Exception:
        return False


# ---------------------------------------------------------------------------
# process-wide plane
# ---------------------------------------------------------------------------

_plane: Optional[TunePlane] = None
_plane_pinned = False
_plane_lock = threading.Lock()


def get_tuneplane() -> TunePlane:
    """The process-default plane.  Re-resolved when
    ``SMLTPU_TUNE_TABLE_DIR`` changes (the supervisor sets it in worker
    env BEFORE the worker constructs engines), unless a plane was pinned
    via :func:`set_tuneplane`."""
    global _plane
    with _plane_lock:
        env_dir = os.environ.get(TUNE_TABLE_ENV) or None
        if _plane is None or (not _plane_pinned
                              and _plane.directory != env_dir):
            _plane = TunePlane(env_dir)
        return _plane


def set_tuneplane(plane: Optional[TunePlane]) -> Optional[TunePlane]:
    """Swap the process-default plane (tests, the bench) → the previous
    one.  ``None`` unpins and reverts to env resolution."""
    global _plane, _plane_pinned
    with _plane_lock:
        prev = _plane
        _plane = plane
        _plane_pinned = plane is not None
        return prev
