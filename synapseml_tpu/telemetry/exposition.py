"""Prometheus text + JSON exposition of a MetricsRegistry.

``render_prometheus`` emits the text format (version 0.0.4) a Prometheus
scraper expects; ``render_json`` emits the registry snapshot for humans
and tests.  :class:`synapseml_tpu.serving.server.ServingServer` serves
both on ``GET /metrics`` (reserved path).
"""

from __future__ import annotations

import json
import math
from typing import Optional

from .registry import MetricsRegistry, get_registry

__all__ = ["render_prometheus", "render_json", "PROMETHEUS_CONTENT_TYPE"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_labels(labelnames, key, extra=()) -> str:
    pairs = [f'{ln}="{_escape_label(lv)}"'
             for ln, lv in zip(labelnames, key)]
    pairs += [f'{ln}="{_escape_label(lv)}"' for ln, lv in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt_value(v: float) -> str:
    # the text format has literal NaN/±Inf spellings — a poisoned gauge
    # must render, not kill every subsequent scrape
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    registry = registry or get_registry()
    lines = []
    for m in registry.metrics():
        lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for key, val in sorted(m.series().items()):
            if m.kind == "histogram":
                for bound, n in zip(m.buckets, val["buckets"]):
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(m.labelnames, key, [('le', _fmt_value(bound))])}"
                        f" {n}")
                lines.append(
                    f"{m.name}_bucket"
                    f"{_fmt_labels(m.labelnames, key, [('le', '+Inf')])}"
                    f" {val['count']}")
                lines.append(f"{m.name}_sum"
                             f"{_fmt_labels(m.labelnames, key)}"
                             f" {_fmt_value(val['sum'])}")
                lines.append(f"{m.name}_count"
                             f"{_fmt_labels(m.labelnames, key)}"
                             f" {val['count']}")
            else:
                lines.append(f"{m.name}{_fmt_labels(m.labelnames, key)}"
                             f" {_fmt_value(val)}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_json(registry: Optional[MetricsRegistry] = None) -> str:
    registry = registry or get_registry()
    return json.dumps(registry.snapshot(), sort_keys=True)
