"""Windowed SLO plane: sliding-window percentile digests + live
attainment/burn-rate gauges for the serving path.

The serving plane already exports cumulative counters and whole-run
histograms — fine for dashboards, useless for an autoscaler: a
counter's lifetime total says nothing about the last minute, which is
the signal a resize decision needs (ROADMAP item 4).  This module is
the telemetry half of that loop:

- :class:`WindowedHistogram` — a time-sliced cumulative-bucket digest:
  observations land in the slice owning ``now``, slices older than the
  window roll off, and quantiles come from bucket interpolation
  (:func:`~synapseml_tpu.telemetry.registry.bucket_quantile`), so live
  p50/p95/p99 need no raw-sample retention and are accurate to within
  one bucket width.
- :class:`WindowedCounter` — the same slice ring counting events
  (admissions, sheds, retirements → windowed rates).
- :class:`SloWindow` — one serving plane's window set: TTFT +
  per-token-latency digests (on the serving-tuned bucket ladders),
  occupancy samples, admission/shed/retirement counts, and declared
  *objectives* (``threshold_s`` + ``target``) from which it computes
  **attainment** (fraction of windowed observations under the
  threshold) and **burn rate** ((1 − attainment) / (1 − target): 1.0
  = burning error budget exactly at the sustainable rate, >1 = an SLO
  violation in progress).
- :class:`SloStore` — the process-wide get-or-create registry of
  windows; its :meth:`~SloStore.snapshot` is the schema-checked JSON
  served at the reserved ``GET /sloz`` path — deliberately the exact
  input contract for the ROADMAP-item-4 autoscaler.

Everything exports live to ``/metrics`` too (``slo_attainment``,
``slo_burn_rate``, ``slo_window_quantile_seconds``,
``slo_window_shed_ratio``, ``slo_window_occupancy``), so a Prometheus
alert and the ``/sloz`` consumer read the same windows.

Stdlib-only; importable before (and without) jax.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from .registry import (SERVING_TOKEN_LATENCY_BUCKETS, SERVING_TTFT_BUCKETS,
                       bucket_quantile, get_registry)

__all__ = ["WindowedHistogram", "WindowedCounter", "SloWindow", "SloStore",
           "get_slo_store", "check_sloz", "SLOZ_SCHEMA",
           "SLOZ_SCHEMA_VERSION", "SLO_METRICS",
           "DEFAULT_WINDOW_S", "DEFAULT_SLICES",
           "TENANT_PLANE_SEP", "tenant_plane_name", "plane_tenant",
           "PHASE_PLANE_SEP", "phase_plane_name", "plane_phase"]

#: default sliding-window length (seconds) and slice count — six 10 s
#: slices: the window advances in 10 s steps, so the digest spans
#: between 50 and 60 s of traffic at any instant
DEFAULT_WINDOW_S = 60.0
DEFAULT_SLICES = 6

#: required top-level keys of a ``/sloz`` snapshot
SLOZ_SCHEMA = ("schema_version", "generated_unix", "window_s", "planes")

#: the ``/sloz`` contract version every snapshot is stamped with.  The
#: unversioned PR-13 payload is retroactively version 1; version 2 is
#: the first STAMPED shape (identical fields plus the stamp itself).
#: Bump on any change to the plane-block layout — ``check_sloz``
#: rejects a mismatched stamp, so a consumer built against this module
#: (the autoscaler is the second consumer after ``/sloz`` itself) can
#: never silently misread a snapshot from a different contract era.
SLOZ_SCHEMA_VERSION = 2

#: SLO-plane metric names (the metric-hygiene sweep holds every one of
#: these to the docs bar, like GANG_METRICS)
SLO_METRICS = frozenset({
    "slo_attainment", "slo_burn_rate", "slo_window_quantile_seconds",
    "slo_window_shed_ratio", "slo_window_occupancy",
    # session-affinity visibility (registered by serving.distributed):
    # part of the same serving-observability plane, same docs bar
    "serving_affinity_total",
})

#: quantiles every window exports (gauge label + snapshot fields)
_QUANTILES: Tuple[Tuple[str, float], ...] = (
    ("p50", 0.50), ("p95", 0.95), ("p99", 0.99))

#: separator embedding a tenant id in a plane name.  Per-tenant SLO
#: attribution rides the EXISTING get-or-create plane registry — a
#: tenant's plane is just ``<base>@tenant=<id>`` — so ``/sloz`` needs
#: no schema change (version 2 holds) and ``/sloz?tenant=`` is a pure
#: plane-name filter.
TENANT_PLANE_SEP = "@tenant="


def tenant_plane_name(base: str, tenant: str) -> str:
    """The plane name carrying ``base``'s per-tenant window for
    ``tenant`` (e.g. ``"/llm@tenant=acme"``)."""
    return f"{base}{TENANT_PLANE_SEP}{tenant}"


def plane_tenant(name: str) -> Optional[str]:
    """The tenant a plane name is attributed to (None for aggregate
    planes)."""
    if TENANT_PLANE_SEP not in name:
        return None
    return name.split(TENANT_PLANE_SEP, 1)[1]


#: separator embedding a serving phase in a plane name — the
#: disaggregated prefill/decode mirror of :data:`TENANT_PLANE_SEP`.
#: A phase's plane is just ``<base>@phase=<prefill|decode>`` riding the
#: same get-or-create registry, so ``/sloz`` needs no schema change
#: (version 2 still holds, exactly as per-tenant planes established)
#: and ``/sloz?phase=`` is a pure plane-name filter the autoscaler can
#: scale each pool off independently.
PHASE_PLANE_SEP = "@phase="


def phase_plane_name(base: str, phase: str) -> str:
    """The plane name carrying ``base``'s per-phase window for
    ``phase`` (e.g. ``"/generate@phase=prefill"``)."""
    return f"{base}{PHASE_PLANE_SEP}{phase}"


def plane_phase(name: str) -> Optional[str]:
    """The serving phase a plane name is attributed to (None for
    aggregate and per-tenant planes)."""
    if PHASE_PLANE_SEP not in name:
        return None
    return name.split(PHASE_PLANE_SEP, 1)[1]


def _num(v) -> Optional[float]:
    """JSON-safe numeric: non-finite (empty-window NaN) → None."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


class _SliceRing:
    """Shared slice mechanics: a deque of ``[slice_index, payload]``
    entries, rotated on every touch so entries older than the window
    roll off.  ``slice_index = floor(now / slice_s)``; the live window
    is the newest ``slices`` indices."""

    def __init__(self, window_s: float, slices: int):
        if window_s <= 0 or slices < 1:
            raise ValueError("window_s must be > 0 and slices >= 1")
        self.window_s = float(window_s)
        self.slices = int(slices)
        self.slice_s = self.window_s / self.slices
        self._ring: Deque[List[Any]] = deque()
        self._lock = threading.Lock()

    def _rotate(self, now: float) -> int:
        idx = int(now // self.slice_s)
        while self._ring and self._ring[0][0] <= idx - self.slices:
            self._ring.popleft()
        return idx

    def _slot(self, now: float, fresh) -> Any:
        idx = self._rotate(now)
        if not self._ring or self._ring[-1][0] != idx:
            self._ring.append([idx, fresh()])
        return self._ring[-1][1]

    def _live(self, now: float) -> List[Any]:
        self._rotate(now)
        return [payload for _, payload in self._ring]


class WindowedHistogram(_SliceRing):
    """Sliding-window cumulative-bucket histogram (thread-safe).

    Same bucket semantics as the registry
    :class:`~synapseml_tpu.telemetry.registry.Histogram`
    (``buckets[i]`` counts observations <= ``bounds[i]``), but scoped
    to the trailing window instead of the process lifetime — quantiles
    and means describe the last ``window_s`` seconds of traffic."""

    def __init__(self, buckets: Sequence[float],
                 window_s: float = DEFAULT_WINDOW_S,
                 slices: int = DEFAULT_SLICES):
        super().__init__(window_s, slices)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("need at least one bucket bound")
        self.buckets: Tuple[float, ...] = bounds

    def _fresh(self):
        # per-slice counts are NON-cumulative (one bisect + one
        # increment per observe — this sits on the serving hot path,
        # once per token); merged() cumulates at read time, which is
        # where the Prometheus-shaped view is actually needed
        return {"buckets": [0] * len(self.buckets), "sum": 0.0, "count": 0}

    def observe(self, value: float, now: Optional[float] = None) -> None:
        value = float(value)
        if math.isnan(value):
            return
        now = time.monotonic() if now is None else now
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            st = self._slot(now, self._fresh)
            if i < len(self.buckets):
                st["buckets"][i] += 1
            st["sum"] += value
            st["count"] += 1

    def merged(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The window's CUMULATIVE buckets/sum/count (Prometheus
        semantics — ``buckets[i]`` = observations <= ``bounds[i]``),
        all live slices summed."""
        now = time.monotonic() if now is None else now
        out = self._fresh()
        with self._lock:
            for st in self._live(now):
                for i, n in enumerate(st["buckets"]):
                    out["buckets"][i] += n
                out["sum"] += st["sum"]
                out["count"] += st["count"]
        run = 0
        for i, n in enumerate(out["buckets"]):
            run += n
            out["buckets"][i] = run
        return out

    def count(self, now: Optional[float] = None) -> int:
        return int(self.merged(now)["count"])

    def mean(self, now: Optional[float] = None) -> float:
        m = self.merged(now)
        return m["sum"] / m["count"] if m["count"] else float("nan")

    def quantile(self, q: float, now: Optional[float] = None) -> float:
        """Bucket-interpolated windowed quantile (NaN when empty)."""
        m = self.merged(now)
        return bucket_quantile(self.buckets, m["buckets"], m["count"], q)

    def fraction_below(self, threshold: float,
                       now: Optional[float] = None) -> float:
        """Interpolated fraction of windowed observations <= threshold
        — the attainment estimator (exact when the threshold sits on a
        bucket bound, which is why SLO thresholds should)."""
        m = self.merged(now)
        if not m["count"]:
            return float("nan")
        prev_bound, prev_cum = 0.0, 0
        for bound, cum in zip(self.buckets, m["buckets"]):
            if threshold <= bound:
                width = bound - prev_bound
                frac = ((threshold - prev_bound) / width) if width > 0 \
                    else 1.0
                est = prev_cum + (cum - prev_cum) * min(1.0, max(0.0, frac))
                return est / m["count"]
            prev_bound, prev_cum = float(bound), int(cum)
        return 1.0 if threshold >= self.buckets[-1] else 0.0


class WindowedCounter(_SliceRing):
    """Sliding-window event counter → windowed rates (thread-safe)."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 slices: int = DEFAULT_SLICES):
        super().__init__(window_s, slices)

    def inc(self, amount: float = 1.0, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            slot = self._slot(now, lambda: [0.0])
            slot[0] += amount

    def count(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            return float(sum(s[0] for s in self._live(now)))

    def rate(self, now: Optional[float] = None) -> float:
        """Events per second over the window (window-length normalized
        — a conservative under-estimate while the first window fills)."""
        return self.count(now) / self.window_s


class SloWindow:
    """One serving plane's windowed SLO state.

    Feed it from the serving loop (``observe_ttft`` /
    ``observe_token_latency`` per event, ``observe_occupancy`` per
    step, ``count("admitted"|"shed"|"retired")`` per transition),
    declare objectives with :meth:`set_objective`, and read back
    either the live ``/metrics`` gauges (:meth:`export_gauges`) or the
    ``/sloz`` snapshot block (:meth:`snapshot`)."""

    #: counter kinds the rates block reports
    KINDS = ("admitted", "shed", "retired")

    def __init__(self, name: str, window_s: float = DEFAULT_WINDOW_S,
                 slices: int = DEFAULT_SLICES):
        self.name = name
        self.window_s = float(window_s)
        self.slices = int(slices)
        self._ttft = WindowedHistogram(SERVING_TTFT_BUCKETS, window_s,
                                       slices)
        self._token = WindowedHistogram(SERVING_TOKEN_LATENCY_BUCKETS,
                                        window_s, slices)
        # occupancy is a fraction in [0, 1]: a fine uniform ladder makes
        # the windowed mean/quantiles sharp at every load level
        self._occ = WindowedHistogram(
            tuple(i / 16 for i in range(1, 17)), window_s, slices)
        self._counts = {k: WindowedCounter(window_s, slices)
                        for k in self.KINDS}
        #: signal -> (threshold_s, target attainment)
        self.objectives: Dict[str, Tuple[float, float]] = {}
        reg = get_registry()
        self._g_attain = reg.gauge(
            "slo_attainment", "windowed fraction of observations meeting "
            "the declared objective", ("plane", "signal"))
        self._g_burn = reg.gauge(
            "slo_burn_rate", "(1 - attainment) / (1 - target): 1.0 burns "
            "error budget exactly at the sustainable rate", ("plane",
                                                             "signal"))
        self._g_quant = reg.gauge(
            "slo_window_quantile_seconds",
            "windowed latency quantile (bucket-interpolated)",
            ("plane", "signal", "quantile"))
        self._g_shed = reg.gauge(
            "slo_window_shed_ratio",
            "windowed sheds / (sheds + admissions)", ("plane",))
        self._g_occ = reg.gauge(
            "slo_window_occupancy", "windowed mean slot occupancy",
            ("plane",))

    # -- feeding -----------------------------------------------------------
    def observe_ttft(self, seconds: float,
                     now: Optional[float] = None) -> None:
        self._ttft.observe(seconds, now)

    def observe_token_latency(self, seconds: float,
                              now: Optional[float] = None) -> None:
        self._token.observe(seconds, now)

    def observe_occupancy(self, fraction: float,
                          now: Optional[float] = None) -> None:
        self._occ.observe(fraction, now)

    def count(self, kind: str, amount: float = 1.0,
              now: Optional[float] = None) -> None:
        self._counts[kind].inc(amount, now)

    def set_objective(self, signal: str, threshold_s: float,
                      target: float = 0.99) -> None:
        """Declare an SLO: ``signal`` in ``ttft``/``token_latency``,
        ``threshold_s`` the latency bound, ``target`` the attainment
        goal the burn rate is normalized against."""
        if signal not in ("ttft", "token_latency"):
            raise ValueError(f"unknown SLO signal {signal!r}")
        self.objectives[signal] = (float(threshold_s),
                                   min(0.9999, max(0.0, float(target))))

    # -- reading -----------------------------------------------------------
    def _signal(self, signal: str) -> WindowedHistogram:
        return self._ttft if signal == "ttft" else self._token

    def attainment(self, signal: str,
                   now: Optional[float] = None) -> float:
        thr, _ = self.objectives[signal]
        return self._signal(signal).fraction_below(thr, now)

    def burn_rate(self, signal: str, now: Optional[float] = None) -> float:
        thr, target = self.objectives[signal]
        att = self._signal(signal).fraction_below(thr, now)
        return (1.0 - att) / (1.0 - target)

    def shed_ratio(self, now: Optional[float] = None) -> float:
        shed = self._counts["shed"].count(now)
        admitted = self._counts["admitted"].count(now)
        total = shed + admitted
        return shed / total if total else 0.0

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """This plane's ``/sloz`` block (all leaves numeric-or-null)."""
        now = time.monotonic() if now is None else now
        signals: Dict[str, Any] = {}
        for sig, hist in (("ttft", self._ttft),
                          ("token_latency", self._token)):
            block = {"count": int(hist.count(now)),
                     "mean_s": _num(hist.mean(now))}
            for label, q in _QUANTILES:
                block[f"{label}_s"] = _num(hist.quantile(q, now))
            signals[sig] = block
        slo: Dict[str, Any] = {}
        for sig, (thr, target) in self.objectives.items():
            slo[sig] = {"threshold_s": thr, "target": target,
                        "attainment": _num(self.attainment(sig, now)),
                        "burn_rate": _num(self.burn_rate(sig, now))}
        rates = {f"{k}_per_s": _num(self._counts[k].rate(now))
                 for k in self.KINDS}
        rates["shed_ratio"] = _num(self.shed_ratio(now))
        return {"window_s": self.window_s, "slices": self.slices,
                "signals": signals,
                "occupancy": {"mean": _num(self._occ.mean(now)),
                              "samples": int(self._occ.count(now))},
                "rates": rates, "slo": slo}

    def export_gauges(self, now: Optional[float] = None) -> None:
        """Refresh this plane's live gauges from the windows (the
        serving loop calls this on a ~1 s cadence; empty windows export
        NaN, which the exposition renders as literal ``NaN``)."""
        now = time.monotonic() if now is None else now
        for sig, hist in (("ttft", self._ttft),
                          ("token_latency", self._token)):
            for label, q in _QUANTILES:
                self._g_quant.set(hist.quantile(q, now), plane=self.name,
                                  signal=sig, quantile=label)
        for sig in self.objectives:
            self._g_attain.set(self.attainment(sig, now),
                               plane=self.name, signal=sig)
            self._g_burn.set(self.burn_rate(sig, now),
                             plane=self.name, signal=sig)
        self._g_shed.set(self.shed_ratio(now), plane=self.name)
        occ = self._occ.mean(now)
        self._g_occ.set(0.0 if math.isnan(occ) else occ, plane=self.name)


class SloStore:
    """Get-or-create registry of :class:`SloWindow` planes; the
    ``/sloz`` endpoint serves :meth:`snapshot`."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._windows: Dict[str, SloWindow] = {}

    def window(self, name: str, window_s: float = DEFAULT_WINDOW_S,
               slices: int = DEFAULT_SLICES) -> SloWindow:
        with self._lock:
            w = self._windows.get(name)
            if w is None:
                w = self._windows[name] = SloWindow(name, window_s, slices)
            return w

    def windows(self) -> List[SloWindow]:
        with self._lock:
            return sorted(self._windows.values(), key=lambda w: w.name)

    def snapshot(self) -> Dict[str, Any]:
        """The full ``/sloz`` payload (validated by :func:`check_sloz`
        before it is served — a malformed window is a 500, never a
        silently wrong autoscaler input).  The top-level ``window_s``
        is the registered planes' COMMON window length; planes with
        differing windows make it null (each plane block always
        carries its own), so a consumer can never misread a custom
        window by trusting a hardcoded top-level value."""
        windows = self.windows()
        lengths = {w.window_s for w in windows}
        common = (lengths.pop() if len(lengths) == 1
                  else DEFAULT_WINDOW_S if not lengths else None)
        return {"schema_version": SLOZ_SCHEMA_VERSION,
                "generated_unix": time.time(),
                "window_s": common,
                "planes": {w.name: w.snapshot() for w in windows}}

    def reset(self) -> None:
        with self._lock:
            self._windows.clear()


#: per-plane block keys check_sloz requires
_PLANE_KEYS = ("window_s", "slices", "signals", "occupancy", "rates", "slo")
_SIGNAL_KEYS = ("count", "mean_s", "p50_s", "p95_s", "p99_s")
_SLO_KEYS = ("threshold_s", "target", "attainment", "burn_rate")


def check_sloz(obj: Any, tenant: Optional[str] = None,
               phase: Optional[str] = None) -> None:
    """Validate a ``/sloz`` snapshot (raises ``ValueError``): required
    keys at every level, every leaf numeric or null — the contract the
    ROADMAP-item-4 autoscaler consumes.  With ``tenant`` set the
    snapshot must additionally be a tenant-filtered view: every plane
    name carries exactly that tenant (the ``/sloz?tenant=`` contract —
    a filter that leaked another tenant's plane is a validation error,
    not a smaller bug).  ``phase`` is the same contract for the
    disaggregated ``/sloz?phase=`` view: every plane name must carry
    exactly that serving phase."""
    if not isinstance(obj, dict):
        raise ValueError("sloz snapshot must be a dict")
    for key in SLOZ_SCHEMA:
        if key not in obj:
            raise ValueError(f"sloz snapshot missing key {key!r}")
    version = obj["schema_version"]
    if version != SLOZ_SCHEMA_VERSION:
        raise ValueError(
            f"sloz schema_version {version!r} unsupported (this consumer "
            f"speaks version {SLOZ_SCHEMA_VERSION}); refusing to guess at "
            "a foreign contract era")
    if not isinstance(obj["planes"], dict):
        raise ValueError("sloz planes must be a dict")
    if tenant is not None:
        for name in obj["planes"]:
            if plane_tenant(name) != tenant:
                raise ValueError(
                    f"sloz plane {name!r} leaked into the tenant="
                    f"{tenant!r} filtered view")
    if phase is not None:
        for name in obj["planes"]:
            if plane_phase(name) != phase:
                raise ValueError(
                    f"sloz plane {name!r} leaked into the phase="
                    f"{phase!r} filtered view")

    def _leaf(path: str, v: Any) -> None:
        if v is not None and not isinstance(v, (int, float)):
            raise ValueError(f"sloz {path} must be numeric or null, "
                             f"got {v!r}")
        if isinstance(v, float) and not math.isfinite(v):
            raise ValueError(f"sloz {path} is non-finite")

    _leaf("generated_unix", obj["generated_unix"])
    _leaf("window_s", obj["window_s"])
    for name, plane in obj["planes"].items():
        for key in _PLANE_KEYS:
            if key not in plane:
                raise ValueError(f"sloz plane {name!r} missing {key!r}")
        for sig in ("ttft", "token_latency"):
            block = plane["signals"].get(sig)
            if not isinstance(block, dict):
                raise ValueError(f"sloz plane {name!r} missing signal "
                                 f"{sig!r}")
            for key in _SIGNAL_KEYS:
                if key not in block:
                    raise ValueError(
                        f"sloz plane {name!r} signal {sig!r} missing "
                        f"{key!r}")
                _leaf(f"{name}.{sig}.{key}", block[key])
        for key, v in plane["occupancy"].items():
            _leaf(f"{name}.occupancy.{key}", v)
        for key, v in plane["rates"].items():
            _leaf(f"{name}.rates.{key}", v)
        for sig, block in plane["slo"].items():
            for key in _SLO_KEYS:
                if key not in block:
                    raise ValueError(
                        f"sloz plane {name!r} slo {sig!r} missing {key!r}")
                _leaf(f"{name}.slo.{sig}.{key}", block[key])


_default_store: Optional[SloStore] = None
_default_lock = threading.Lock()


def get_slo_store() -> SloStore:
    """The process-wide SLO store every serving loop feeds."""
    global _default_store
    if _default_store is None:
        with _default_lock:
            if _default_store is None:
                _default_store = SloStore()
    return _default_store
