"""Crash flight recorder: a bounded, allocation-stable ring of structured
events, dumped SIGKILL-atomically for post-mortem bundles.

PR 4's gang supervision tears a failed gang down with only bounded log
tails as evidence — every rank's counters, spans and step timings die
with its process, so a hung/killed rank yields a verdict string but no
structured trace of *what it was doing*.  The flight recorder closes
that gap the way an aircraft FDR does: every instrumented layer writes
compact events into a fixed-size in-process ring (collective begin/end
with op/axis/bytes, checkpoint publishes, retry/backoff firings, fault
injections, heartbeat emits, rowguard verdicts), and the ring's tail is

- exported live over the gang wire (``SMLMP_TM:`` batches — see
  :mod:`synapseml_tpu.telemetry.gangplane`), so the driver holds a
  near-current tail even for a rank that dies by SIGKILL, and
- dumped to a per-rank file on signal/teardown with the same
  tmp + fsync + rename discipline as :mod:`.artifact` — a kill at the
  ``flight.dump`` fault site leaves the previous bundle (or nothing),
  never a torn file.

Allocation-stable: the ring is a preallocated slot list written in
place; recording never grows it, so a recorder left on in production
costs one lock + one tuple store per event and a fixed memory ceiling.

Stdlib-only; importable before (and without) jax, from any layer.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from .artifact import dumps_checked

__all__ = ["FlightRecorder", "get_flight", "record", "sanitize_floats",
           "FLIGHT_SCHEMA", "DEFAULT_CAPACITY", "CAPACITY_ENV"]

#: ring capacity (events) unless overridden per recorder or via env
DEFAULT_CAPACITY = 512
#: env var overriding the process-default recorder's capacity
CAPACITY_ENV = "SMLTPU_FLIGHT_EVENTS"

#: required top-level keys of a dumped flight record
FLIGHT_SCHEMA = ("events", "last_seq")


def sanitize_floats(obj):
    """NaN/Inf → string, recursively: the artifact writer rejects
    non-finite floats by design (``allow_nan=False``), and one poisoned
    gauge or event field must not abort a crash dump or post-mortem."""
    if isinstance(obj, float):
        if obj != obj or obj in (float("inf"), float("-inf")):
            return repr(obj)
        return obj
    if isinstance(obj, dict):
        return {k: sanitize_floats(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [sanitize_floats(v) for v in obj]
    return obj


class FlightRecorder:
    """Fixed-capacity ring of ``(seq, ts, kind, fields)`` events.

    Thread-safe; ``enabled=False`` turns :meth:`record` into a single
    attribute read (the bench's paired off leg).  ``seq`` is a
    monotonically increasing per-recorder counter, so consumers (the
    gang wire, the post-mortem gather) can express "events since" and
    compare the freshness of two tails of the same rank.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.enabled = True
        # REENTRANT: the worker's SIGTERM handler dumps the ring from the
        # main thread, which may have been interrupted INSIDE record()'s
        # critical section — a plain Lock would self-deadlock there (and
        # the rank would miss its grace window and lose the dump to the
        # follow-up SIGKILL).  The worst a reentrant read sees is a seq
        # one ahead of its slot — acceptable for a crash artifact.
        self._lock = threading.RLock()
        # preallocated slots, written in place — the ring never grows
        self._slots: List[Optional[tuple]] = [None] * self.capacity
        self._next = 0          # slot index the next event lands in
        self._seq = 0           # total events ever recorded

    # -- recording ---------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        """Append one structured event (dropped oldest-first once the
        ring is full).  Never raises — a telemetry write must not break
        the instrumented code path."""
        if not self.enabled:
            return
        try:
            ts = time.time()
            with self._lock:
                self._seq += 1
                self._slots[self._next] = (self._seq, ts, kind, fields)
                self._next = (self._next + 1) % self.capacity
        except Exception:
            pass

    # -- reading -----------------------------------------------------------
    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def _ordered(self) -> List[tuple]:
        # oldest → newest: the slots after the cursor wrapped earlier
        with self._lock:
            head = self._slots[self._next:] + self._slots[:self._next]
        return [s for s in head if s is not None]

    @staticmethod
    def _as_dict(slot: tuple) -> Dict[str, Any]:
        seq, ts, kind, fields = slot
        return {"seq": seq, "ts": ts, "kind": kind, **fields}

    def events(self) -> List[Dict[str, Any]]:
        """Every retained event, oldest first."""
        return [self._as_dict(s) for s in self._ordered()]

    def tail(self, n: int) -> List[Dict[str, Any]]:
        return [self._as_dict(s) for s in self._ordered()[-max(0, n):]]

    def events_since(self, seq: int,
                     limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Events with ``seq`` strictly greater than the given watermark
        (capped at the newest ``limit`` when set) — the gang wire's
        incremental-export primitive."""
        out = [self._as_dict(s) for s in self._ordered() if s[0] > seq]
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def clear(self) -> None:
        with self._lock:
            self._slots = [None] * self.capacity
            self._next = 0
            self._seq = 0

    # -- dumping -----------------------------------------------------------
    def dump(self, path: str, rank: Optional[int] = None,
             extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """SIGKILL-atomic dump of the whole ring to ``path``.

        Same discipline as :func:`~synapseml_tpu.telemetry.artifact.
        write_json`, inlined so the ``flight.dump`` kill point sits at
        the worst possible instant — temp file written and fsynced, the
        rename still ahead: a SIGKILL there leaves only the invisible
        temp file, never a torn ``path``.  Safe to call from a signal
        handler (pure-python IO)."""
        payload: Dict[str, Any] = {
            "rank": rank, "last_seq": self.last_seq,
            "dumped_unix": time.time(), "events": self.events()}
        if extra:
            payload.update(extra)
        text = dumps_checked(sanitize_floats(payload), schema=FLIGHT_SCHEMA)
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=directory, prefix=os.path.basename(path) + ".tmp.")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                f.write(text)
                if not text.endswith("\n"):
                    f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.chmod(tmp, 0o644)
            # the atomicity fault site: ``kill`` armed here SIGKILLs the
            # process with the temp file complete but unpublished — the
            # test that proves "no partial bundle" observes exactly this
            try:
                from ..resilience.faults import get_faults
                get_faults().kill_point("flight.dump", path=path)
            except ImportError:      # pragma: no cover - stripped builds
                pass
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        try:
            dfd = os.open(directory, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:  # pragma: no cover - platform without dir fsync
            pass
        return payload


_default: Optional[FlightRecorder] = None
_default_lock = threading.Lock()


def get_flight() -> FlightRecorder:
    """The process-wide recorder every built-in layer writes into."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                try:
                    cap = int(os.environ.get(CAPACITY_ENV, "") or
                              DEFAULT_CAPACITY)
                except ValueError:
                    cap = DEFAULT_CAPACITY
                _default = FlightRecorder(capacity=max(1, cap))
    return _default


def record(kind: str, **fields) -> None:
    """``flight.record(...)`` on the process-default recorder."""
    get_flight().record(kind, **fields)
