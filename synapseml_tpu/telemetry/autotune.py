"""Measured autotuning harness + fitted collective cost model — the
measurement half of the self-tuning performance plane (ROADMAP item 3).

Five chip-side tuning remainders (Pallas paged-attention tile, GBDT
histogram chunk, prefill/span bucket grids, int8 chunk size, the
planner's link-class cost model) consolidate into ONE subsystem:

- a :class:`TuneSpace` names a search space, the REAL jitted entry
  point its candidates dispatch through (held by a tier-1 source-scan
  lint to ``warmup.REGISTERED_ENTRY_POINTS`` — no tuning of programs
  the compile plane can't warm), and a ``build()`` hook producing the
  concrete ``(candidate config, runner)`` trials for this process;
- :meth:`Autotuner.run` warms every candidate (compiles are not the
  measurement), times them through
  :meth:`StepProfiler.measure`'s alternating min-of-blocks protocol,
  and persists the winner into the
  :mod:`~synapseml_tpu.telemetry.tunetable` — every trial observable
  (``autotune_trials_total{space,outcome}`` + flight events carrying
  measured ms and cost-analysis bytes, a roofline block per winner);
- :class:`CollectiveCostModel` fits per-link α-β (latency s, s/byte)
  from measured dispatch timings across payload sizes — the synthesis
  formulation of arXiv:2110.10548, with the ring/tree baselines of
  Horovod (arXiv:1802.05799) and the quantized two-level EQuARX
  (arXiv:2506.17615) as the strategies it prices — and derives the
  planner's tree-vs-ring payload crossover from the fit.  With no fit
  loaded the model degrades to the spec constants (``spec`` source) and
  the planner's decisions stay byte-identical to the hardcoded cutoff.

The honesty rule is inherited from the table: an empty candidate set
(kernel can't run on this backend) records NOTHING; measured numbers
are real wall clock on THIS process's backend, keyed by its
``device_kind`` — a CPU interpret-mode measurement can never be
mistaken for a chip's.
"""

from __future__ import annotations

import dataclasses
import importlib
import math
import threading
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple)

from .flight import record as flight_record
from .gangplane import StepProfiler
from .registry import get_registry
from .tunetable import TunePlane, geometry_key, get_tuneplane

__all__ = [
    "AUTOTUNE_METRICS", "TuneSpace", "Autotuner",
    "register_space", "registered_spaces", "resolve_entry_point",
    "fit_alpha_beta", "CollectiveCostModel", "COST_MODEL_SPACE",
    "COST_MODEL_GEOMETRY",
]

#: metrics this module (and the table loader) own — the metric-hygiene
#: sweep + docs contract
AUTOTUNE_METRICS = frozenset({
    "autotune_trials_total",
    "autotune_table_consults_total",
})

#: the tuning-table space/geometry the planner's fitted model loads from
COST_MODEL_SPACE = "collective_cost_model"
COST_MODEL_GEOMETRY = "link=ici"


def resolve_entry_point(spec: str):
    """``"pkg.mod:fn"`` → the function object, verified to be a REAL
    jitted entry point: it must be registered in
    ``warmup.REGISTERED_ENTRY_POINTS[pkg.mod]`` and duck-type as a jit
    wrapper (``lower`` + ``_cache_size``).  Raises ``ValueError``
    otherwise — a search space can never time a program the compile
    plane cannot warm."""
    mod_name, _, fn_name = str(spec).partition(":")
    if not mod_name or not fn_name:
        raise ValueError(f"entry point {spec!r}: want 'module:function'")
    from ..models.llm.warmup import REGISTERED_ENTRY_POINTS
    registered = REGISTERED_ENTRY_POINTS.get(mod_name)
    if registered is None or fn_name not in registered:
        raise ValueError(
            f"entry point {spec!r} is not in REGISTERED_ENTRY_POINTS — "
            "register it with the warmup lattice (models/llm/warmup.py) "
            "before tuning through it")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name, None)
    if fn is None or not (hasattr(fn, "lower")
                          and hasattr(fn, "_cache_size")):
        raise ValueError(f"entry point {spec!r} did not resolve to a "
                         "module-level jitted function")
    return fn


@dataclasses.dataclass(frozen=True)
class TuneSpace:
    """One registered search space.

    ``build(**ctx)`` returns ``(geometry, trials)`` where ``geometry``
    is the :func:`~synapseml_tpu.telemetry.tunetable.geometry_key` the
    winner is recorded under (and the one the construction site
    consults with), and ``trials`` is a list of
    ``(candidate_config, runner)`` pairs — ``runner()`` dispatches the
    entry point with the candidate applied and blocks until done.  An
    optional third element ``cost()`` returns an XLA cost-analysis dict
    (``flops``/``bytes_accessed``) for the candidate's compiled
    program, carried on the trial's flight event and the winner's
    roofline block.  An EMPTY trial list means nothing is measurable on
    this backend — the harness claims nothing.

    ``ctx`` parameterizes the geometry (a test tunes the exact tiny
    geometry its engine will consult with; the bench uses the
    representative defaults).
    """
    name: str
    entry_point: str
    build: Callable[..., Tuple[str, List[tuple]]]
    description: str = ""


_SPACES: Dict[str, TuneSpace] = {}
_spaces_lock = threading.Lock()
_builtin_done = False


def register_space(space: TuneSpace) -> TuneSpace:
    with _spaces_lock:
        _SPACES[space.name] = space
    return space


def registered_spaces() -> Dict[str, TuneSpace]:
    """Name → space, builtin spaces included (registered lazily; their
    ``build`` hooks import jax-heavy modules only when run)."""
    _ensure_builtin_spaces()
    with _spaces_lock:
        return dict(_SPACES)


# ---------------------------------------------------------------------------
# the harness
# ---------------------------------------------------------------------------

class Autotuner:
    """Enumerate → warm → measure → persist, one space at a time.

    Timing is :meth:`StepProfiler.measure`'s multi-leg protocol: every
    candidate runs once per block, leg order reversing block to block,
    statistic = per-candidate minimum across ``blocks`` blocks ("how
    fast CAN this candidate go" — contention only inflates a block).
    """

    def __init__(self, plane: Optional[TunePlane] = None,
                 blocks: int = 3):
        self._plane = plane
        self.blocks = max(1, int(blocks))
        self._c_trials = get_registry().counter(
            "autotune_trials_total",
            "autotune candidate trials, by search space and outcome "
            "(ok = measured; error = candidate raised; empty = nothing "
            "measurable on this backend)", ("space", "outcome"))

    @property
    def plane(self) -> TunePlane:
        return self._plane if self._plane is not None else get_tuneplane()

    def run(self, space: TuneSpace, persist: bool = True,
            **ctx: Any) -> Optional[dict]:
        """Measure every candidate of ``space`` → result dict
        (``winner``, ``measured_ms``, per-candidate ``trials_ms``,
        ``roofline``), persisting the winner into the tuning table.
        ``None`` when the space has no measurable candidates here."""
        resolve_entry_point(space.entry_point)   # fail fast, pre-measure
        geometry, trials = space.build(**ctx)
        legs: Dict[str, Callable[[], Any]] = {}
        configs: Dict[str, dict] = {}
        costs: Dict[str, Optional[dict]] = {}
        for trial in trials:
            cand, runner = trial[0], trial[1]
            cost_fn = trial[2] if len(trial) > 2 else None
            label = ",".join(f"{k}={v}" for k, v in sorted(cand.items()))
            # warm first: the compile is the lattice's job, not part of
            # the measurement; a candidate that cannot even run once is
            # an error trial, not a slow one
            try:
                runner()
            except Exception as e:
                self._c_trials.inc(1, space=space.name, outcome="error")
                flight_record("autotune_trial", space=space.name,
                              geometry=geometry, candidate=label,
                              outcome="error", error=repr(e))
                continue
            legs[label] = runner
            configs[label] = dict(cand)
            costs[label] = _safe_cost(cost_fn)
        if not legs:
            self._c_trials.inc(1, space=space.name, outcome="empty")
            flight_record("autotune_trial", space=space.name,
                          geometry=geometry, outcome="empty")
            return None

        measured = StepProfiler.measure(legs, blocks=self.blocks)
        for label, seconds in measured.items():
            self._c_trials.inc(1, space=space.name, outcome="ok")
            event = {"space": space.name, "geometry": geometry,
                     "candidate": label, "outcome": "ok",
                     "measured_ms": seconds * 1e3}
            cost = costs.get(label)
            if cost:
                event["cost_bytes"] = cost.get("bytes_accessed")
                event["cost_flops"] = cost.get("flops")
            flight_record("autotune_trial", **event)

        winner_label = min(measured, key=lambda k: measured[k])
        winner_ms = measured[winner_label] * 1e3
        result = {
            "space": space.name,
            "geometry": geometry,
            "winner": configs[winner_label],
            "measured_ms": winner_ms,
            "trial_count": len(measured),
            "trials_ms": {k: v * 1e3 for k, v in measured.items()},
            "roofline": self._winner_roofline(space.name, winner_label,
                                              measured[winner_label],
                                              costs.get(winner_label)),
        }
        if persist and self.plane.directory:
            self.plane.record(space.name, geometry, configs[winner_label],
                              winner_ms, trials=len(measured))
        return result

    def _winner_roofline(self, space_name: str, label: str,
                         seconds: float, cost: Optional[dict]) -> dict:
        """One StepProfiler step accounting the winner's measured time
        as compute (+ its cost-analysis entry when the candidate
        captured one) → the profiler's roofline-ready summary block."""
        prof = StepProfiler(f"autotune_{space_name}")
        prof.step_begin(0)
        prof._open["t_last"] -= seconds   # attribute the measured time
        prof.mark("compute")
        if cost:
            prof.costs[label] = dict(cost)
        prof.step_end()
        return prof.summary()

    def run_all(self, persist: bool = True) -> Dict[str, Optional[dict]]:
        return {name: self.run(space, persist=persist)
                for name, space in sorted(registered_spaces().items())}


def _safe_cost(cost_fn) -> Optional[dict]:
    if cost_fn is None:
        return None
    try:
        cost = cost_fn()
        return dict(cost) if cost else None
    except Exception:
        return None


def _cost_of(jitted, *args, **kw) -> Optional[dict]:
    """XLA cost analysis of a compiled call (flops / bytes_accessed),
    None where the backend doesn't expose it."""
    try:
        analysis = jitted.lower(*args, **kw).compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if not analysis:
            return None
        out = {}
        for k in ("flops", "bytes accessed", "bytes_accessed"):
            if k in analysis:
                out[k.replace(" ", "_")] = float(analysis[k])
        return out or None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# builtin search spaces
# ---------------------------------------------------------------------------

def _interpret_mode() -> bool:
    """Pallas kernels run in interpret mode off-TPU (the test-suite
    convention); measured ms stay honest because the table keys them by
    this process's device_kind."""
    import jax
    return jax.default_backend() != "tpu"


def _build_paged_attn_tile(max_len: int = 256, num_heads: int = 4,
                           num_kv_heads: int = 2, d_head: int = 64,
                           n_slots: int = 4, span: int = 1):
    """Candidates: every tile the VMEM/divisibility gate admits at this
    geometry; runner: one decode step of the paged kernel over full
    spans (the worst-case bucketed grid)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models.llm import pallas_attn

    dtype = jnp.float32
    geometry = pallas_attn.paged_geometry_key(max_len, num_kv_heads,
                                              d_head, dtype, span)
    interpret = _interpret_mode()
    rng = np.random.default_rng(0)
    q_shape = ((n_slots, num_heads, d_head) if span == 1
               else (n_slots, span, num_heads, d_head))
    q = jnp.asarray(rng.standard_normal(q_shape), dtype)
    k = jnp.asarray(rng.standard_normal(
        (n_slots, max_len, num_kv_heads, d_head)), dtype)
    v = jnp.asarray(rng.standard_normal(
        (n_slots, max_len, num_kv_heads, d_head)), dtype)
    spans = jnp.full((n_slots,), max_len, jnp.int32)
    trials = []
    for tile in pallas_attn._TILE_CANDIDATES:
        geo = pallas_attn.paged_geometry(max_len, num_heads, num_kv_heads,
                                         d_head, dtype=dtype,
                                         max_query_span=span, tile=tile)
        if geo is None:
            continue

        def runner(tile=tile, nt=geo.total_tiles):
            jax.block_until_ready(pallas_attn.paged_decode_attention(
                q, k, v, spans, tile=tile, num_tiles=nt,
                interpret=interpret))

        def cost(tile=tile, nt=geo.total_tiles):
            return _cost_of(pallas_attn.paged_decode_attention,
                            q, k, v, spans, tile=tile, num_tiles=nt,
                            interpret=interpret)

        trials.append(({"tile": int(tile)}, runner, cost))
    return geometry, trials


def _build_gbdt_hist_chunk(num_features: int = 16, total_bins: int = 256,
                           n_slots: int = 2, n_rows: Optional[int] = None):
    """Candidates: the legal row-chunk overrides for the histogram
    kernels (``hist_chunk_ok``); runner: one node-batched histogram
    build over a PAD_MULTIPLE row block."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models.gbdt import pallas_hist as ph

    N = int(n_rows) if n_rows else ph.PAD_MULTIPLE
    geometry = geometry_key(features=int(num_features),
                            total_bins=int(total_bins))
    interpret = _interpret_mode()
    rng = np.random.default_rng(0)
    bins_t = jnp.asarray(
        rng.integers(0, total_bins, (num_features, N)), jnp.int32)
    slot = jnp.asarray(rng.integers(0, n_slots, (N,)), jnp.int32)
    grad = jnp.asarray(rng.standard_normal(N), jnp.float32)
    hess = jnp.asarray(rng.uniform(0.5, 1.5, N), jnp.float32)
    mask = jnp.ones((N,), jnp.float32)
    vals, scales = ph.prep_hist_vals(grad, hess, mask)
    trials = []
    for chunk in (1024, 2048, 4096):
        if N % chunk or not ph.hist_chunk_ok(num_features, total_bins,
                                             n_slots, chunk):
            continue

        def runner(chunk=chunk):
            jax.block_until_ready(ph.build_hist_nodes_pallas(
                bins_t, slot, vals, scales, n_slots, total_bins,
                interpret=interpret, hist_chunk=chunk))

        def cost(chunk=chunk):
            return _cost_of(ph.build_hist_nodes_pallas,
                            bins_t, slot, vals, scales, n_slots,
                            total_bins, interpret=interpret,
                            hist_chunk=chunk)

        trials.append(({"chunk": int(chunk)}, runner, cost))
    return geometry, trials


def _build_llm_bucket_grid(max_len: int = 64, num_layers: int = 2,
                           prompt_lens: Sequence[int] = (5, 11, 23),
                           candidates: Sequence[int] = (4, 8, 16)):
    """Candidates: the bucket-grid floor (``min_bucket``); runner: an
    admit+cancel cycle over representative prompt lengths — a finer
    grid pays less prefill padding, a coarser one compiles fewer
    programs.  Heavier build than the kernel spaces (constructs one
    tiny engine per candidate), sized accordingly."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models.llm import LlamaConfig, LlamaModel, SlotEngine

    geometry = geometry_key(max_len=int(max_len))
    cfg = LlamaConfig.tiny(num_layers=int(num_layers), max_len=int(max_len),
                           dtype=jnp.float32)
    model = LlamaModel(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 8), jnp.int32))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, (int(n),)).astype(np.int32)
               for n in prompt_lens if int(n) < max_len]
    trials = []
    for mb in candidates:
        mb = int(mb)
        if mb < 1 or mb > max_len or (mb & (mb - 1)):
            continue
        eng = SlotEngine(model, variables, n_slots=1, max_len=max_len,
                         min_bucket=mb)

        def runner(eng=eng):
            for prompt in prompts:
                res = eng.admit(prompt, max_new_tokens=2)
                eng.cancel(res.slot)

        trials.append(({"min_bucket": mb}, runner))
    return geometry, trials


def _build_int8_chunk(numel: int = 1 << 18,
                      candidates: Sequence[int] = (64, 128, 256, 512,
                                                   1024)):
    """Candidates: the int8 codec's quantization-chunk size; runner: a
    full encode+decode round trip of a representative flat gradient."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..parallel import compression as comp

    numel = int(numel)
    geometry = geometry_key(numel=numel)
    rng = np.random.default_rng(0)
    flat = jnp.asarray(rng.standard_normal(numel), jnp.float32)
    trials = []
    for chunk in candidates:
        chunk = int(chunk)
        if chunk < 8 or numel % chunk:
            continue

        def runner(chunk=chunk):
            jax.block_until_ready(comp.int8_roundtrip_jit(flat, chunk))

        def cost(chunk=chunk):
            return _cost_of(comp.int8_roundtrip_jit, flat, chunk)

        trials.append(({"chunk": chunk}, runner, cost))
    return geometry, trials


def _ensure_builtin_spaces() -> None:
    global _builtin_done
    with _spaces_lock:
        if _builtin_done:
            return
        _builtin_done = True
    for space in (
        TuneSpace(
            name="paged_attn_tile",
            entry_point="synapseml_tpu.models.llm.pallas_attn:"
                        "paged_decode_attention",
            build=_build_paged_attn_tile,
            description="paged decode-attention K/V tile length"),
        TuneSpace(
            name="gbdt_hist_chunk",
            entry_point="synapseml_tpu.models.gbdt.pallas_hist:"
                        "build_hist_nodes_pallas",
            build=_build_gbdt_hist_chunk,
            description="GBDT histogram-kernel rows-per-chunk"),
        TuneSpace(
            name="llm_bucket_grid",
            entry_point="synapseml_tpu.models.llm.slots:_prefill_slot_jit",
            build=_build_llm_bucket_grid,
            description="prefill/span bucket-grid floor (min_bucket)"),
        TuneSpace(
            name="int8_chunk",
            entry_point="synapseml_tpu.parallel.compression:"
                        "int8_roundtrip_jit",
            build=_build_int8_chunk,
            description="int8 codec quantization-chunk size"),
    ):
        register_space(space)


# ---------------------------------------------------------------------------
# fitted collective cost model
# ---------------------------------------------------------------------------

def fit_alpha_beta(samples: Sequence[Tuple[float, float]]
                   ) -> Tuple[float, float]:
    """Closed-form least squares of ``t(n) = α + β·n`` over
    ``(payload_bytes, seconds)`` samples → ``(alpha_s,
    beta_s_per_byte)``.  Needs measurements at ≥ 2 distinct payload
    sizes; raises ``ValueError`` otherwise — a fit that would have to
    invent a slope is no fit (the honesty rule)."""
    pts = [(float(n), float(t)) for n, t in samples]
    if any(not math.isfinite(n) or not math.isfinite(t) for n, t in pts):
        raise ValueError("fit_alpha_beta: non-finite sample")
    if len(pts) < 2 or len({n for n, _ in pts}) < 2:
        raise ValueError(
            "fit_alpha_beta needs measurements at >= 2 distinct payload "
            f"sizes, got {len(pts)} samples")
    n = len(pts)
    mx = sum(x for x, _ in pts) / n
    my = sum(y for _, y in pts) / n
    sxx = sum((x - mx) ** 2 for x, _ in pts)
    sxy = sum((x - mx) * (y - my) for x, y in pts)
    beta = sxy / sxx
    alpha = my - beta * mx
    return alpha, beta


class CollectiveCostModel:
    """α-β pricing of collective routes, feeding the planner's
    ``_decide``.

    Per-hop transfer time is ``t(n) = α + β·n``.  A recursive-doubling
    tree over ``w`` pow-2 ranks pays ``L = log2(w)`` serial hops of the
    full payload: ``L·(α + β·n)``; a ring all-reduce pays ``2(w-1)``
    hops of ``n/w``: ``2(w-1)·(α + β·n/w)``.  The tree wins while the
    latency term dominates; the crossover payload is::

        n* = α · (2(w-1) − L) / (β · (L − 2(w-1)/w))

    (for ``w = 2`` the bandwidth coefficients tie and the tree's single
    hop always wins — the crossover is unbounded).

    ``source`` is the provenance label on every plan
    (``collective_plans_total{model=...}``): ``fitted`` = α-β from real
    measured dispatch timings via the tuning table; ``spec`` = the
    hardcoded cutoff constant + ``CHIP_ICI_BW`` table — the fallback,
    whose decisions are byte-identical to the pre-model planner.
    """

    #: "the tree always wins" sentinel cutoff (w = 2, or degenerate fits)
    UNBOUNDED = 1 << 62

    def __init__(self, alpha_s: float = 0.0,
                 beta_s_per_byte: float = 0.0,
                 source: str = "spec",
                 spec_cutoff_bytes: Optional[int] = None):
        if source not in ("fitted", "spec"):
            raise ValueError(f"cost-model source {source!r}")
        if source == "fitted":
            a, b = float(alpha_s), float(beta_s_per_byte)
            if not (math.isfinite(a) and math.isfinite(b)
                    and a >= 0.0 and b > 0.0):
                raise ValueError(
                    f"fitted cost model needs alpha >= 0 and beta > 0, got "
                    f"alpha={alpha_s!r} beta={beta_s_per_byte!r} — a flat "
                    "or negative slope cannot price bandwidth; refusing "
                    "rather than extrapolating")
        self.alpha_s = float(alpha_s)
        self.beta_s_per_byte = float(beta_s_per_byte)
        self.source = source
        self._spec_cutoff = (int(spec_cutoff_bytes)
                             if spec_cutoff_bytes is not None else None)

    @classmethod
    def fitted(cls, samples: Sequence[Tuple[float, float]]
               ) -> "CollectiveCostModel":
        a, b = fit_alpha_beta(samples)
        return cls(max(0.0, a), b, source="fitted")

    @classmethod
    def spec(cls, cutoff_bytes: int) -> "CollectiveCostModel":
        return cls(source="spec", spec_cutoff_bytes=cutoff_bytes)

    def predict_s(self, nbytes: int) -> Optional[float]:
        """Per-hop transfer seconds (fitted models only)."""
        if self.source != "fitted":
            return None
        return self.alpha_s + self.beta_s_per_byte * max(0, int(nbytes))

    def tree_cutoff_bytes(self, world: int) -> int:
        """Payloads ≤ this ride the latency-optimal tree (the planner's
        small-payload branch).  Spec models return the constant they
        were built with; fitted models derive the crossover above."""
        if self.source == "spec":
            if self._spec_cutoff is None:
                raise ValueError("spec cost model built without a cutoff")
            return self._spec_cutoff
        w = max(2, int(world))
        L = math.ceil(math.log2(w))
        ring_hops = 2 * (w - 1)
        coeff = L - ring_hops / w
        if coeff <= 0:
            return self.UNBOUNDED
        n_star = self.alpha_s * (ring_hops - L) / (self.beta_s_per_byte
                                                   * coeff)
        if not math.isfinite(n_star) or n_star >= self.UNBOUNDED:
            return self.UNBOUNDED
        return max(0, int(n_star))

    def describe(self) -> dict:
        return {"source": self.source,
                "alpha_us": self.alpha_s * 1e6,
                "beta_us_per_mib": self.beta_s_per_byte * 1e6 * (1 << 20),
                "spec_cutoff_bytes": self._spec_cutoff}
