"""Atomic, schema-checked JSON artifact IO.

Round 5's bench artifact shipped truncated (``BENCH_r05.json`` carried a
cut-off stdout tail and ``"parsed": null``), losing the headline number.
This module makes that class of loss structurally impossible for
anything written through it:

- ``write_json`` serializes, **round-trip parses the serialized text**,
  writes to a temp file in the TARGET directory, ``fsync``\\ s, then
  ``os.replace``\\ s over the destination (plus a directory fsync where
  the platform allows) — a reader never observes a partial file, and a
  crash mid-write leaves the previous version intact.
- after the rename the destination is **read back and parsed again**, so
  the returned object is exactly what a later reader will see.
- an optional ``schema`` (iterable of required top-level keys, or a
  callable validator) rejects structurally wrong payloads before any
  byte hits disk.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Iterable, Optional, Union

__all__ = ["SchemaError", "check_schema", "dumps_checked", "write_json",
           "read_json"]

Schema = Union[Iterable[str], Callable[[Any], None]]


class SchemaError(ValueError):
    """Payload failed the artifact schema check."""


def check_schema(obj: Any, schema: Optional[Schema]) -> None:
    """``schema`` is either a callable ``schema(obj)`` raising on
    mismatch, or an iterable of required top-level dict keys."""
    if schema is None:
        return
    if callable(schema):
        schema(obj)
        return
    if not isinstance(obj, dict):
        raise SchemaError(f"expected a JSON object, got {type(obj).__name__}")
    missing = [k for k in schema if k not in obj]
    if missing:
        raise SchemaError(f"missing required keys: {missing}")


def dumps_checked(obj: Any, schema: Optional[Schema] = None,
                  indent: Optional[int] = None) -> str:
    """Serialize and prove the text parses back (and passes ``schema``)
    BEFORE anyone prints or writes it."""
    text = json.dumps(obj, indent=indent, sort_keys=False,
                      allow_nan=False, default=_jsonify)
    parsed = json.loads(text)
    check_schema(parsed, schema)
    return text


def _jsonify(o: Any):
    """Last-resort encoder: numpy scalars/arrays → python, else str."""
    item = getattr(o, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    tolist = getattr(o, "tolist", None)
    if callable(tolist):
        try:
            return tolist()
        except Exception:
            pass
    return str(o)


def write_json(path: str, obj: Any, schema: Optional[Schema] = None,
               indent: Optional[int] = 2) -> Any:
    """Atomically write ``obj`` as JSON to ``path``; returns the object
    parsed back FROM the renamed file (the round-trip proof)."""
    import tempfile
    text = dumps_checked(obj, schema, indent)
    directory = os.path.dirname(os.path.abspath(path))
    # mkstemp: a pid-only suffix would let two THREADS of one process
    # share (and tear) the temp inode — uniqueness must cover threads
    fd, tmp = tempfile.mkstemp(
        dir=directory, prefix=os.path.basename(path) + ".tmp.")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
            if not text.endswith("\n"):
                f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.chmod(tmp, 0o644)          # mkstemp defaults to 0600
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        # fsync the directory so the rename itself survives power loss
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:  # pragma: no cover - platform without dir fsync
        pass
    return read_json(path, schema)


def read_json(path: str, schema: Optional[Schema] = None) -> Any:
    with open(path, "r", encoding="utf-8") as f:
        obj = json.load(f)
    check_schema(obj, schema)
    return obj
