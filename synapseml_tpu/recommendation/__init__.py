"""Recommendation (reference: core/.../recommendation/)."""

from .evaluator import (RankingEvaluator, RankingTrainValidationSplit,
                        RankingTrainValidationSplitModel,
                        RecommendationIndexer, RecommendationIndexerModel,
                        diversity_at_k, mean_average_precision, ndcg_at_k,
                        precision_at_k, recall_at_k)
from .evaluator import RankingAdapter, RankingAdapterModel
from .sar import SAR, SARModel

__all__ = [
    "RankingAdapter", "RankingAdapterModel",
    "RankingEvaluator", "RankingTrainValidationSplit",
    "RankingTrainValidationSplitModel", "RecommendationIndexer",
    "RecommendationIndexerModel", "SAR", "SARModel", "diversity_at_k",
    "mean_average_precision", "ndcg_at_k", "precision_at_k", "recall_at_k",
]
