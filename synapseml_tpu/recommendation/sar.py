"""SAR — Smart Adaptive Recommendations — on TPU.

Re-designs the reference's Spark SAR (reference: core/.../recommendation/
SAR.scala:36 + SARModel.scala): item-item similarity from co-occurrence
counts and time-decayed user-item affinity, scored as ``affinity @
similarity``.  The Spark build computes co-occurrence with a self-join;
here the user-item interaction matrix A is dense on-device and the
co-occurrence matrix is ONE MXU matmul ``A^T A`` — the all-pairs
similarity the reference assembles row-by-row.  Jaccard / lift
normalizations are elementwise ops XLA fuses into the matmul epilogue.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataset import Dataset
from ..core.params import (FloatParam, IntParam, PyObjectParam, StringParam)
from ..core.pipeline import Estimator, Model


class SAR(Estimator):
    """SAR estimator.

    Params mirror the reference (SAR.scala): ``similarityFunction`` in
    {jaccard, lift, cooccurrence}, ``supportThreshold`` minimum
    co-occurrence count, ``timeDecayCoeff`` half-life (days) applied when
    ``timeCol`` is set.
    """

    userCol = StringParam(doc="user id column", default="user")
    itemCol = StringParam(doc="item id column", default="item")
    ratingCol = StringParam(doc="rating column", default="rating")
    timeCol = StringParam(doc="timestamp column (seconds) for decay")
    similarityFunction = StringParam(
        doc="item-item similarity normalization", default="jaccard",
        allowed=("jaccard", "lift", "cooccurrence"))
    supportThreshold = IntParam(doc="min co-occurrence support", default=4)
    timeDecayCoeff = IntParam(doc="affinity half-life in days", default=30)

    def _fit(self, ds: Dataset) -> "SARModel":
        users_raw = ds[self.userCol]
        items_raw = ds[self.itemCol]
        user_vocab, user_idx = np.unique(users_raw, return_inverse=True)
        item_vocab, item_idx = np.unique(items_raw, return_inverse=True)
        n_u, n_i = len(user_vocab), len(item_vocab)

        ratings = (ds[self.ratingCol].astype(np.float32)
                   if self.ratingCol in ds else np.ones(ds.num_rows,
                                                        np.float32))
        # -- affinity: time-decayed sum of ratings (SAR.scala affinity) ----
        time_col = self.get("timeCol")
        if time_col and time_col in ds:
            t = ds[time_col].astype(np.float64)
            ref = t.max()
            half_life_s = float(self.timeDecayCoeff) * 86400.0
            decay = np.power(2.0, -(ref - t) / half_life_s).astype(np.float32)
            weights = ratings * decay
        else:
            weights = ratings
        affinity = np.zeros((n_u, n_i), np.float32)
        np.add.at(affinity, (user_idx, item_idx), weights)

        # -- co-occurrence on the MXU: C = B^T B, B = binarized A ----------
        seen = np.zeros((n_u, n_i), np.float32)
        seen[user_idx, item_idx] = 1.0
        cooc = np.asarray(
            jax.jit(lambda b: (b.T @ b))(jnp.asarray(seen)))

        thresh = float(self.supportThreshold)
        cooc = np.where(cooc >= thresh, cooc, 0.0)
        diag = np.diag(cooc).copy()
        fn = self.similarityFunction
        if fn == "cooccurrence":
            sim = cooc
        else:
            with np.errstate(divide="ignore", invalid="ignore"):
                if fn == "jaccard":
                    denom = diag[:, None] + diag[None, :] - cooc
                else:  # lift
                    denom = diag[:, None] * diag[None, :]
                sim = np.where(denom > 0, cooc / denom, 0.0)
        sim = sim.astype(np.float32)

        model = SARModel()
        model.set("userVocabulary", user_vocab)
        model.set("itemVocabulary", item_vocab)
        model.set("userAffinity", affinity)
        model.set("itemSimilarity", sim)
        model.set("seenItems", seen)
        model._copy_values_from(self)
        return model


class SARModel(Model):
    userCol = StringParam(doc="user id column", default="user")
    itemCol = StringParam(doc="item id column", default="item")
    ratingCol = StringParam(doc="rating column", default="rating")
    predictionCol = StringParam(doc="score output column",
                                default="prediction")
    recommendationsCol = StringParam(doc="top-k output column",
                                     default="recommendations")
    userVocabulary = PyObjectParam(doc="user id vocabulary")
    itemVocabulary = PyObjectParam(doc="item id vocabulary")
    userAffinity = PyObjectParam(doc="(U, I) affinity matrix")
    itemSimilarity = PyObjectParam(doc="(I, I) similarity matrix")
    seenItems = PyObjectParam(doc="(U, I) binary seen matrix")

    def _scores(self) -> np.ndarray:
        """(U, I) recommendation scores = affinity @ similarity (one MXU
        matmul; SARModel.recommendForAllUsers analogue)."""
        aff = jnp.asarray(self.get("userAffinity"))
        sim = jnp.asarray(self.get("itemSimilarity"))
        return np.asarray(jax.jit(jnp.matmul)(aff, sim))

    def _transform(self, ds: Dataset) -> Dataset:
        """Score explicit (user, item) pairs.  Only the affinity rows of
        the users actually present are multiplied against the similarity
        matrix — not the full (U, I) score matrix."""
        user_vocab = np.asarray(self.get("userVocabulary"))
        item_vocab = np.asarray(self.get("itemVocabulary"))
        u_map = {u: i for i, u in enumerate(user_vocab)}
        i_map = {v: i for i, v in enumerate(item_vocab)}
        users = ds[self.userCol]
        items = ds[self.itemCol]
        u_idx = np.array([u_map.get(u, -1) for u in users], np.int64)
        i_idx = np.array([i_map.get(v, -1) for v in items], np.int64)
        known = (u_idx >= 0) & (i_idx >= 0)
        out = np.zeros(ds.num_rows, np.float32)
        if known.any():
            uniq_u, local = np.unique(u_idx[known], return_inverse=True)
            aff = jnp.asarray(
                np.asarray(self.get("userAffinity"))[uniq_u])
            sim = jnp.asarray(self.get("itemSimilarity"))
            sub_scores = np.asarray(jax.jit(jnp.matmul)(aff, sim))
            out[known] = sub_scores[local, i_idx[known]]
        return ds.with_column(self.predictionCol, out)

    def recommend_for_all_users(self, k: int,
                                remove_seen: bool = True) -> Dataset:
        user_vocab = np.asarray(self.get("userVocabulary"))
        item_vocab = np.asarray(self.get("itemVocabulary"))
        scores = jnp.asarray(self._scores())
        if remove_seen:
            seen = jnp.asarray(self.get("seenItems"))
            scores = jnp.where(seen > 0, -jnp.inf, scores)
        k = min(k, scores.shape[1])
        vals, idx = jax.jit(lambda s: jax.lax.top_k(s, k))(scores)
        vals, idx = np.asarray(vals), np.asarray(idx)
        recs = np.empty(len(user_vocab), dtype=object)
        for u in range(len(user_vocab)):
            recs[u] = [{"item": item_vocab[j], "rating": float(v)}
                       for j, v in zip(idx[u], vals[u]) if np.isfinite(v)]
        return Dataset({self.userCol: user_vocab,
                        self.recommendationsCol: recs})
