"""Ranking metrics + recommendation indexing.

Re-designs the reference's ranking tooling (reference: core/.../
recommendation/RankingEvaluator.scala, RecommendationIndexer.scala,
RankingTrainValidationSplit.scala).  Metrics are computed over padded
(U, k) prediction / (U, m) ground-truth id matrices in one vectorized
pass instead of Spark's RankingMetrics RDD job.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.dataset import Dataset
from ..core.params import (FloatParam, IntParam, PyObjectParam, StringParam)
from ..core.pipeline import Estimator, Evaluator, Model, Transformer


def _as_id_lists(col: np.ndarray) -> List[List]:
    """Normalize a column to per-user id lists; SAR-style recommendation
    dicts ({'item': ..., 'rating': ...}) are unwrapped to their item id so
    metric set operations stay hashable."""
    def unwrap(e):
        return e.get("item", e.get("value")) if isinstance(e, dict) else e

    out = []
    for v in col:
        if isinstance(v, (list, tuple, np.ndarray)):
            out.append([unwrap(e) for e in v])
        else:
            out.append([unwrap(v)])
    return out


def precision_at_k(pred: List[List], actual: List[List], k: int) -> float:
    vals = []
    for p, a in zip(pred, actual):
        if not a:
            continue
        hits = len(set(p[:k]) & set(a))
        vals.append(hits / k)
    return float(np.mean(vals)) if vals else 0.0


def recall_at_k(pred: List[List], actual: List[List], k: int) -> float:
    vals = []
    for p, a in zip(pred, actual):
        if not a:
            continue
        hits = len(set(p[:k]) & set(a))
        vals.append(hits / len(a))
    return float(np.mean(vals)) if vals else 0.0


def ndcg_at_k(pred: List[List], actual: List[List], k: int) -> float:
    vals = []
    for p, a in zip(pred, actual):
        if not a:
            continue
        aset = set(a)
        dcg = sum(1.0 / np.log2(i + 2) for i, x in enumerate(p[:k])
                  if x in aset)
        idcg = sum(1.0 / np.log2(i + 2) for i in range(min(len(a), k)))
        vals.append(dcg / idcg if idcg > 0 else 0.0)
    return float(np.mean(vals)) if vals else 0.0


def mean_average_precision(pred: List[List], actual: List[List],
                           k: Optional[int] = None) -> float:
    vals = []
    for p, a in zip(pred, actual):
        if not a:
            continue
        aset = set(a)
        p_k = p[:k] if k else p
        hits, score = 0, 0.0
        for i, x in enumerate(p_k):
            if x in aset:
                hits += 1
                score += hits / (i + 1)
        vals.append(score / min(len(a), len(p_k)) if p_k else 0.0)
    return float(np.mean(vals)) if vals else 0.0


def diversity_at_k(pred: List[List], all_items: int, k: int) -> float:
    """Fraction of the catalogue covered by the union of top-k lists
    (RankingEvaluator diversityAtK)."""
    rec = set()
    for p in pred:
        rec.update(p[:k])
    return len(rec) / max(all_items, 1)


class RankingEvaluator(Evaluator):
    """Evaluate per-user ranked predictions
    (reference: RankingEvaluator.scala; metric names match)."""

    k = IntParam(doc="cutoff", default=10)
    metricName = StringParam(doc="metric", default="ndcgAt",
                             allowed=("ndcgAt", "map", "precisionAtk",
                                      "recallAtK", "diversityAtK",
                                      "maxDiversity"))
    predictionCol = StringParam(doc="per-user predicted id list",
                                default="prediction")
    labelCol = StringParam(doc="per-user ground-truth id list",
                           default="label")
    nItems = IntParam(doc="catalogue size for diversity metrics", default=-1)

    def evaluate(self, ds: Dataset) -> float:
        pred = _as_id_lists(ds[self.predictionCol])
        actual = _as_id_lists(ds[self.labelCol])
        k = int(self.k)
        name = self.metricName
        if name == "ndcgAt":
            return ndcg_at_k(pred, actual, k)
        if name == "map":
            return mean_average_precision(pred, actual)
        if name == "precisionAtk":
            return precision_at_k(pred, actual, k)
        if name == "recallAtK":
            return recall_at_k(pred, actual, k)
        n_items = int(self.nItems)
        if n_items <= 0:
            n_items = len({x for lst in pred + actual for x in lst})
        if name == "diversityAtK":
            return diversity_at_k(pred, n_items, k)
        if name == "maxDiversity":
            rec = {x for lst in pred for x in lst[:k]}
            act = {x for lst in actual for x in lst}
            return len(rec | act) / max(n_items, 1)
        raise ValueError(name)

    def is_larger_better(self) -> bool:
        return True


class RecommendationIndexer(Estimator):
    """String user/item ids -> contiguous int indices
    (reference: RecommendationIndexer.scala)."""

    userInputCol = StringParam(doc="raw user column", default="user")
    userOutputCol = StringParam(doc="indexed user column", default="userIdx")
    itemInputCol = StringParam(doc="raw item column", default="item")
    itemOutputCol = StringParam(doc="indexed item column", default="itemIdx")

    def _fit(self, ds: Dataset) -> "RecommendationIndexerModel":
        users = np.unique(ds[self.userInputCol])
        items = np.unique(ds[self.itemInputCol])
        model = RecommendationIndexerModel()
        model.set("userVocabulary", users)
        model.set("itemVocabulary", items)
        model._copy_values_from(self)
        return model


class RecommendationIndexerModel(Model):
    userInputCol = StringParam(doc="raw user column", default="user")
    userOutputCol = StringParam(doc="indexed user column", default="userIdx")
    itemInputCol = StringParam(doc="raw item column", default="item")
    itemOutputCol = StringParam(doc="indexed item column", default="itemIdx")
    userVocabulary = PyObjectParam(doc="user vocabulary")
    itemVocabulary = PyObjectParam(doc="item vocabulary")

    def _transform(self, ds: Dataset) -> Dataset:
        u_map = {u: i for i, u in enumerate(
            np.asarray(self.get("userVocabulary")))}
        i_map = {v: i for i, v in enumerate(
            np.asarray(self.get("itemVocabulary")))}
        u_idx = np.array([u_map.get(u, -1) for u in ds[self.userInputCol]],
                         np.int64)
        i_idx = np.array([i_map.get(v, -1) for v in ds[self.itemInputCol]],
                         np.int64)
        return ds.with_columns({self.userOutputCol: u_idx,
                                self.itemOutputCol: i_idx})

    def recover_user(self, idx: np.ndarray) -> np.ndarray:
        return np.asarray(self.get("userVocabulary"))[np.asarray(idx)]

    def recover_item(self, idx: np.ndarray) -> np.ndarray:
        return np.asarray(self.get("itemVocabulary"))[np.asarray(idx)]


def _top_k_actuals(ds: Dataset, user_col: str, item_col: str,
                   rating_col: str, k: int) -> Dict[Any, List]:
    """Per-user ground-truth item lists, windowed by rating desc / item
    asc and truncated to k (reference: RankingAdapter.scala transform's
    Window + rank <= k).  Null ratings sort last (Spark desc default);
    non-comparable item ties fall back to string ordering rather than
    raising."""
    has_rating = rating_col in ds.columns
    rows_by_user: Dict[Any, List] = {}
    for r in ds.iter_rows():
        rating = r[rating_col] if has_rating else 0.0
        # None and NaN both mean "no rating" (NaN is how float columns
        # store nulls here) and must sort last, not poison the sort
        neg = float("inf") if rating is None else -float(rating)
        if neg != neg:                      # NaN rating
            neg = float("inf")
        rows_by_user.setdefault(r[user_col], []).append((neg, r[item_col]))
    out = {}
    for u, rows in rows_by_user.items():
        try:
            ordered = sorted(rows)
        except TypeError:
            ordered = sorted(rows, key=lambda p: (p[0], str(p[1])))
        out[u] = [it for _, it in ordered[:k]]
    return out


class RankingTrainValidationSplit(Estimator):
    """Per-user leave-out split + fit + ranking evaluation
    (reference: RankingTrainValidationSplit.scala).  The estimator must
    produce a model exposing ``recommend_for_all_users``."""

    estimator = PyObjectParam(doc="recommender estimator (e.g. SAR)")
    evaluator = PyObjectParam(doc="RankingEvaluator")
    trainRatio = FloatParam(doc="per-user fraction of events in train",
                            default=0.75)
    userCol = StringParam(doc="user column", default="user")
    itemCol = StringParam(doc="item column", default="item")
    ratingCol = StringParam(doc="rating column for ground-truth ranking",
                            default="rating")
    seed = IntParam(doc="rng seed", default=0)
    minRatingsPerUser = IntParam(doc="drop users with fewer events",
                                 default=1)

    def _fit(self, ds: Dataset) -> "RankingTrainValidationSplitModel":
        rng = np.random.default_rng(int(self.seed))
        users = ds[self.userCol]
        uniq, inv = np.unique(users, return_inverse=True)
        train_mask = np.zeros(ds.num_rows, bool)
        for u in range(len(uniq)):
            rows = np.where(inv == u)[0]
            if len(rows) < int(self.minRatingsPerUser):
                continue
            rng.shuffle(rows)
            n_train = max(1, int(round(len(rows) * float(self.trainRatio))))
            train_mask[rows[:n_train]] = True
        train = ds.filter(train_mask)
        test = ds.filter(~train_mask)

        est: Estimator = self.get("estimator")
        model = est.fit(train)

        ev: RankingEvaluator = self.get("evaluator") or RankingEvaluator()
        k = int(ev.k)
        recs = model.recommend_for_all_users(k)
        rec_map: Dict[Any, List] = {}
        rec_col = recs.columns[1]
        for r in recs.iter_rows():
            rec_map[r[recs.columns[0]]] = [m["item"] for m in r[rec_col]]
        actual_map = _top_k_actuals(test, self.userCol, self.itemCol,
                                    self.ratingCol, k)
        eval_users = [u for u in actual_map if u in rec_map]
        eval_ds = Dataset({
            "user": np.asarray(eval_users, dtype=object),
            ev.predictionCol: [rec_map[u] for u in eval_users],
            ev.labelCol: [actual_map[u] for u in eval_users],
        }) if eval_users else None
        metric = ev.evaluate(eval_ds) if eval_ds is not None else 0.0

        out = RankingTrainValidationSplitModel()
        out.set("bestModel", model)
        out.set("validationMetric", float(metric))
        out._copy_values_from(self)
        return out


class RankingTrainValidationSplitModel(Model):
    userCol = StringParam(doc="user column", default="user")
    itemCol = StringParam(doc="item column", default="item")
    bestModel = PyObjectParam(doc="fitted recommender")
    validationMetric = PyObjectParam(doc="held-out ranking metric")

    def _transform(self, ds: Dataset) -> Dataset:
        return self.get("bestModel").transform(ds)


class RankingAdapter(Estimator):
    """Adapt any recommender estimator for ranking evaluation
    (reference: RankingAdapter.scala — fit the wrapped estimator, then
    ``transform`` emits one row per user with the top-k predicted item
    list and the ground-truth item list, the schema RankingEvaluator
    consumes)."""

    recommender = PyObjectParam(doc="wrapped recommender estimator")
    k = IntParam(doc="recommendations per user", default=10)
    userCol = StringParam(doc="user column", default="user")
    itemCol = StringParam(doc="item column", default="item")
    ratingCol = StringParam(doc="rating column for ground-truth ranking",
                            default="rating")

    def _fit(self, ds: Dataset) -> "RankingAdapterModel":
        model = self.get("recommender").fit(ds)
        out = RankingAdapterModel()
        out.set("recommenderModel", model)
        out._copy_values_from(self)
        return out


class RankingAdapterModel(Model):
    recommenderModel = PyObjectParam(doc="fitted recommender")
    k = IntParam(doc="recommendations per user", default=10)
    userCol = StringParam(doc="user column", default="user")
    itemCol = StringParam(doc="item column", default="item")
    ratingCol = StringParam(doc="rating column for ground-truth ranking",
                            default="rating")

    def _transform(self, ds: Dataset) -> Dataset:
        model = self.get("recommenderModel")
        k = int(self.k)
        recs = model.recommend_for_all_users(k)
        rec_map: Dict[Any, List] = {}
        rec_col = recs.columns[1]
        for r in recs.iter_rows():
            rec_map[r[recs.columns[0]]] = [m["item"] for m in r[rec_col]]
        # ground truth mirrors the reference's Window(rating desc, item asc)
        # + rank <= k truncation (RankingAdapter.scala transform): only each
        # user's top-k actual items count as relevant for recall/MAP/NDCG.
        actual_map = _top_k_actuals(ds, self.userCol, self.itemCol,
                                    self.ratingCol, k)
        users = [u for u in actual_map if u in rec_map]
        return Dataset({
            self.userCol: np.asarray(users, dtype=object),
            "prediction": [rec_map[u] for u in users],
            "label": [actual_map[u] for u in users],
        })
